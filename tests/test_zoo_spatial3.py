"""Round-3 third layer sweep: conv variants, 3-D deconv, spatial norms,
upsampling/resize/crop (SURVEY.md §2.1). Torch oracles where torch has the op."""

import jax.numpy as jnp
import numpy as np
import pytest
import torch
import torch.nn.functional as F

from bigdl_tpu import nn
from bigdl_tpu.utils.random_generator import RandomGenerator


def _np(*shape, seed=0):
    return np.random.default_rng(seed).normal(size=shape).astype(np.float32)


class TestConvVariants:
    def test_share_convolution_matches_spatial(self):
        RandomGenerator.set_seed(0)
        a = nn.SpatialConvolution(2, 4, 3, 3, pad_w=1, pad_h=1)
        b = nn.SpatialShareConvolution(2, 4, 3, 3, pad_w=1, pad_h=1)
        b.set_params(a.get_params())
        x = jnp.asarray(_np(2, 2, 6, 6))
        np.testing.assert_allclose(np.asarray(a.evaluate().forward(x)),
                                   np.asarray(b.evaluate().forward(x)),
                                   rtol=1e-6)

    def test_locally_connected_2d_oracle(self):
        """Validate the patch-einsum against an explicit unfold computation."""
        RandomGenerator.set_seed(0)
        m = nn.LocallyConnected2D(2, 6, 5, 3, 2, 2, stride_w=2, stride_h=1,
                                  pad_w=1, pad_h=0)
        x = _np(2, 2, 5, 6)  # NCHW: H=5 (input_height), W=6 (input_width)
        out = np.asarray(m.evaluate().forward(jnp.asarray(x)))
        w = np.asarray(m.get_params()["weight"])   # (P, O, C*kh*kw)
        b = np.asarray(m.get_params()["bias"])     # (P, O)
        # torch unfold gives (N, C*kh*kw, P) with (c, kh, kw) feature order
        patches = F.unfold(torch.tensor(x), kernel_size=(2, 2),
                           stride=(1, 2), padding=(0, 1)).numpy()
        ref = np.einsum("nkp,pok->npo", patches, w) + b[None]
        ref = ref.transpose(0, 2, 1).reshape(out.shape)
        np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-5)
        assert out.shape == (2, 3, m.out_h, m.out_w)

    def test_locally_connected_1d(self):
        RandomGenerator.set_seed(0)
        m = nn.LocallyConnected1D(7, 3, 4, kernel_w=3, stride_w=2)
        x = _np(2, 7, 3)
        out = np.asarray(m.evaluate().forward(jnp.asarray(x)))
        w = np.asarray(m.get_params()["weight"])
        b = np.asarray(m.get_params()["bias"])
        n_out = (7 - 3) // 2 + 1
        ref = np.zeros((2, n_out, 4), np.float32)
        for p in range(n_out):
            patch = x[:, p * 2:p * 2 + 3, :].reshape(2, -1)
            ref[:, p, :] = patch @ w[p].T + b[p]
        np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-5)


class TestVolumetricFull:
    def test_conv_transpose3d_oracle(self):
        RandomGenerator.set_seed(0)
        m = nn.VolumetricFullConvolution(2, 3, 2, 3, 3, dt=2, dw=1, dh=2,
                                         pad_t=1, pad_w=1, pad_h=0)
        x = _np(1, 2, 4, 5, 6)
        out = np.asarray(m.evaluate().forward(jnp.asarray(x)))
        w = np.asarray(m.get_params()["weight"])   # (I, O, kt, kh, kw)
        b = np.asarray(m.get_params()["bias"])
        ref = F.conv_transpose3d(
            torch.tensor(x), torch.tensor(w), torch.tensor(b),
            stride=(2, 2, 1), padding=(1, 0, 1)).numpy()
        np.testing.assert_allclose(out, ref, rtol=1e-3, atol=1e-4)


class TestSpatialNorms:
    def test_within_channel_lrn_constant(self):
        # constant input: denom = (1 + alpha*c^2)^beta everywhere (SAME border
        # effects only change the SUM, which the interior window saturates)
        x = np.full((1, 2, 9, 9), 2.0, np.float32)
        out = np.asarray(nn.SpatialWithinChannelLRN(3, alpha=1.0, beta=0.5)
                         .evaluate().forward(jnp.asarray(x)))
        interior = out[0, 0, 4, 4]
        np.testing.assert_allclose(interior, 2.0 / np.sqrt(1 + 4.0), rtol=1e-5)

    def test_subtractive_norm_zeroes_constant(self):
        x = np.full((1, 3, 8, 8), 5.0, np.float32)
        out = np.asarray(nn.SpatialSubtractiveNormalization(3, np.ones((5, 5)))
                         .evaluate().forward(jnp.asarray(x)))
        np.testing.assert_allclose(out, np.zeros_like(x), atol=1e-5)

    def test_divisive_norm_scale_invariant_direction(self):
        x = _np(1, 2, 8, 8)
        m = nn.SpatialDivisiveNormalization(2, np.ones((5, 5)))
        out1 = np.asarray(m.evaluate().forward(jnp.asarray(x)))
        out2 = np.asarray(m.evaluate().forward(jnp.asarray(10.0 * x)))
        # dividing by the local std makes the output scale-invariant
        np.testing.assert_allclose(out1, out2, rtol=1e-4, atol=1e-5)

    def test_contrastive_composes(self):
        x = _np(1, 2, 8, 8)
        sub = nn.SpatialSubtractiveNormalization(2).evaluate()
        div = nn.SpatialDivisiveNormalization(2).evaluate()
        both = nn.SpatialContrastiveNormalization(2).evaluate()
        ref = np.asarray(div.forward(sub.forward(jnp.asarray(x))))
        np.testing.assert_allclose(np.asarray(both.forward(jnp.asarray(x))),
                                   ref, rtol=1e-5, atol=1e-6)

    def test_spatial_dropout_1d_3d(self):
        RandomGenerator.set_seed(0)
        x = np.ones((4, 6, 8), np.float32)
        out = np.asarray(nn.SpatialDropout1D(0.5).training()
                         .forward(jnp.asarray(x)))
        # whole channels dropped: each (n, :, c) column is all-0 or all-2
        col = out.reshape(4, 6, 8)
        assert ((col == 0).all(1) | (col == 2).all(1)).all()
        x3 = np.ones((2, 4, 3, 3, 3), np.float32)
        out3 = np.asarray(nn.SpatialDropout3D(0.5).training()
                          .forward(jnp.asarray(x3)))
        flat = out3.reshape(2, 4, -1)
        assert ((flat == 0).all(-1) | (flat == 2).all(-1)).all()


class TestResizeCrop:
    def test_upsampling_1d_2d_3d(self):
        x = _np(2, 3, 4)
        out = np.asarray(nn.UpSampling1D(2).evaluate().forward(jnp.asarray(x)))
        np.testing.assert_allclose(out, np.repeat(x, 2, axis=1))
        x2 = _np(2, 3, 4, 5)
        out2 = np.asarray(nn.UpSampling2D((2, 3)).evaluate()
                          .forward(jnp.asarray(x2)))
        np.testing.assert_allclose(
            out2, np.repeat(np.repeat(x2, 2, axis=2), 3, axis=3))
        x3 = _np(1, 2, 3, 3, 3)
        out3 = np.asarray(nn.UpSampling3D((2, 2, 2)).evaluate()
                          .forward(jnp.asarray(x3)))
        assert out3.shape == (1, 2, 6, 6, 6)

    @pytest.mark.parametrize("align", [False, True])
    def test_resize_bilinear_oracle(self, align):
        x = _np(2, 3, 5, 7)
        out = np.asarray(nn.ResizeBilinear(8, 11, align_corners=align)
                         .evaluate().forward(jnp.asarray(x)))
        ref = F.interpolate(torch.tensor(x), size=(8, 11), mode="bilinear",
                            align_corners=align).numpy()
        np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-5)

    def test_cropping(self):
        x = _np(2, 3, 6, 8)
        out = np.asarray(nn.Cropping2D((1, 2), (3, 0)).evaluate()
                         .forward(jnp.asarray(x)))
        np.testing.assert_allclose(out, x[:, :, 1:4, 3:])
        x3 = _np(1, 2, 4, 5, 6)
        out3 = np.asarray(nn.Cropping3D((1, 1), (0, 2), (2, 1)).evaluate()
                          .forward(jnp.asarray(x3)))
        np.testing.assert_allclose(out3, x3[:, :, 1:3, 0:3, 2:5])


class TestFullConvFlipFix:
    def test_conv_transpose2d_oracle(self):
        """SpatialFullConvolution must match torch deconv (kernel-flip fix)."""
        RandomGenerator.set_seed(0)
        m = nn.SpatialFullConvolution(2, 3, 3, 3, dw=2, dh=2, pad_w=1, pad_h=1)
        x = _np(1, 2, 5, 5)
        out = np.asarray(m.evaluate().forward(jnp.asarray(x)))
        w = np.asarray(m.get_params()["weight"])
        b = np.asarray(m.get_params()["bias"])
        ref = F.conv_transpose2d(torch.tensor(x), torch.tensor(w),
                                 torch.tensor(b), stride=2, padding=1).numpy()
        np.testing.assert_allclose(out, ref, rtol=1e-3, atol=1e-4)


class TestReviewFixesSpatial:
    def test_softmax_with_out_of_range_ignore_label(self):
        logits = _np(4, 3)
        y = np.array([0, 1, 255, 2], np.int32)  # Caffe-style ignore=255
        out = float(nn.SoftmaxWithCriterion(ignore_label=255).forward(
            jnp.asarray(logits), jnp.asarray(y)))
        assert np.isfinite(out)
        keep = y != 255
        ref = F.cross_entropy(torch.tensor(logits[keep]),
                              torch.tensor(y[keep].astype(np.int64))).item()
        np.testing.assert_allclose(out, ref, rtol=1e-5)

    def test_grouped_deconv2d_oracle(self):
        RandomGenerator.set_seed(0)
        m = nn.SpatialFullConvolution(4, 6, 3, 3, dw=2, dh=2, pad_w=1, pad_h=1,
                                      n_group=2)
        x = _np(1, 4, 5, 5)
        out = np.asarray(m.evaluate().forward(jnp.asarray(x)))
        w = np.asarray(m.get_params()["weight"])
        b = np.asarray(m.get_params()["bias"])
        ref = F.conv_transpose2d(torch.tensor(x), torch.tensor(w),
                                 torch.tensor(b), stride=2, padding=1,
                                 groups=2).numpy()
        np.testing.assert_allclose(out, ref, rtol=1e-3, atol=1e-4)

    def test_grouped_deconv3d_oracle(self):
        RandomGenerator.set_seed(0)
        m = nn.VolumetricFullConvolution(4, 6, 2, 2, 2, dt=2, dw=2, dh=2,
                                         n_group=2)
        x = _np(1, 4, 3, 3, 3)
        out = np.asarray(m.evaluate().forward(jnp.asarray(x)))
        w = np.asarray(m.get_params()["weight"])
        b = np.asarray(m.get_params()["bias"])
        ref = F.conv_transpose3d(torch.tensor(x), torch.tensor(w),
                                 torch.tensor(b), stride=2, groups=2).numpy()
        np.testing.assert_allclose(out, ref, rtol=1e-3, atol=1e-4)

    def test_divisive_norm_thresval(self):
        x = np.zeros((1, 1, 9, 9), np.float32)
        x[0, 0, 4, 4] = 1.0
        m = nn.SpatialDivisiveNormalization(1, np.ones((3, 3)),
                                            threshold=1e6, thresval=2.0)
        out = np.asarray(m.evaluate().forward(jnp.asarray(x)))
        # every localstd <= 1e6 -> divisor == thresval everywhere
        np.testing.assert_allclose(out, x / 2.0, rtol=1e-6)

    def test_even_kernel_rejected(self):
        with pytest.raises(ValueError, match="odd"):
            nn.SpatialSubtractiveNormalization(2, np.ones((8, 8)))
        with pytest.raises(ValueError, match="odd"):
            nn.SpatialDivisiveNormalization(2, np.ones((4, 5)))

    def test_device_cache_revalidates_on_dataset_swap(self):
        import numpy as _np2
        from bigdl_tpu import nn as _nn
        from bigdl_tpu.dataset.dataset import DataSet
        from bigdl_tpu.dataset.sample import MiniBatch
        from bigdl_tpu.dataset.transformer import Transformer
        from bigdl_tpu.optim import SGD
        from bigdl_tpu.optim.optimizer import LocalOptimizer
        from bigdl_tpu.optim.trigger import Trigger

        class Ident(Transformer):
            def __call__(self, it):
                return iter(list(it))

        rng = _np2.random.default_rng(0)
        batches = [MiniBatch(rng.normal(size=(4, 5)).astype(_np2.float32),
                             rng.integers(0, 2, size=(4,)).astype(_np2.int32))
                   for _ in range(2)]
        model = _nn.Sequential().add(_nn.Linear(5, 2)).add(_nn.LogSoftMax())
        ds = DataSet.array(batches)
        opt = LocalOptimizer(model, ds, _nn.ClassNLLCriterion())
        opt.set_optim_method(SGD(learningrate=0.1))
        opt.set_end_when(Trigger.max_iteration(2))
        opt.optimize()
        assert opt._device_batch_cache is not None
        opt.dataset = ds >> Ident()  # now yields fresh objects every epoch
        opt.set_end_when(Trigger.max_iteration(4))
        opt.optimize()
        assert opt._device_batch_cache is None  # guard re-ran, cache dropped
