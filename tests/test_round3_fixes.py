"""Round-3 verdict fixes: Engine.init watchdog, optimizer-state continuation,
lazy (batched) loss fetching, named Plateau monitor, batched validation fetch."""

import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from bigdl_tpu import Engine, nn
from bigdl_tpu.dataset import DataSet, SampleToMiniBatch
from bigdl_tpu.dataset.sample import Sample
from bigdl_tpu.optim import LocalOptimizer, SGD, Top1Accuracy, Loss, Trigger
from bigdl_tpu.optim.schedules import Plateau


def _toy_data(n=64, dim=8, classes=3, batch=16, seed=0):
    rng = np.random.default_rng(seed)
    samples = [Sample(rng.normal(size=(dim,)).astype(np.float32),
                      np.int32(rng.integers(0, classes))) for _ in range(n)]
    return DataSet.array(samples) >> SampleToMiniBatch(batch)


def _toy_model(dim=8, classes=3):
    return nn.Sequential().add(nn.Linear(dim, classes)).add(nn.LogSoftMax())


class TestInitWatchdog:
    def test_timeout_raises_with_diagnostic(self, monkeypatch):
        Engine.reset()
        monkeypatch.setenv("BIGDL_INIT_TIMEOUT", "0.2")

        def hang(*a, **kw):
            time.sleep(10)

        monkeypatch.setattr(jax, "devices", hang)
        with pytest.raises(RuntimeError, match="BIGDL_INIT_TIMEOUT"):
            Engine.init()
        assert not Engine.is_initialized()

    def test_discovery_error_propagates(self, monkeypatch):
        Engine.reset()

        def boom(*a, **kw):
            raise ValueError("no such backend")

        monkeypatch.setattr(jax, "devices", boom)
        with pytest.raises(ValueError, match="no such backend"):
            Engine.init()

    def test_zero_timeout_disables_watchdog(self, monkeypatch):
        Engine.reset()
        monkeypatch.setenv("BIGDL_INIT_TIMEOUT", "0")
        Engine.init()
        assert Engine.is_initialized()


class TestOptimizerStateContinuation:
    def test_momentum_survives_reoptimize(self):
        """A second optimize() on the same Optimizer must carry the SGD momentum
        slots forward (round-2 bench bug: the timed leg re-ran init_state)."""
        Engine.init(seed=0)
        data = _toy_data()
        opt = LocalOptimizer(_toy_model(), data, nn.ClassNLLCriterion())
        method = SGD(learningrate=0.1, momentum=0.9, dampening=0.0)
        opt.set_optim_method(method)
        opt.set_end_when(Trigger.max_iteration(3))
        opt.optimize()
        v1 = [np.asarray(x) for x in jax.tree_util.tree_leaves(opt._final_ostate["v"])]
        assert any(np.abs(l).max() > 0 for l in v1)  # momentum accumulated

        # continuation: init_state must NOT be re-run (it would zero the slots)
        def forbidden(params):
            raise AssertionError("init_state re-run on continuation")

        method.init_state = forbidden
        opt.set_end_when(Trigger.max_iteration(6))
        opt.optimize()
        assert opt.state["neval"] >= 6
        v2 = [np.asarray(x) for x in jax.tree_util.tree_leaves(opt._final_ostate["v"])]
        # slots kept evolving from v1, not from zero
        assert any(np.abs(a - b).max() > 0 for a, b in zip(v1, v2))


class TestLazyLossFetch:
    def test_log_every_preserves_exact_summaries(self, tmp_path):
        """With log_every=5 the loss is fetched in batches, but every iteration's
        exact loss must still land in the event file."""
        from bigdl_tpu.visualization import TrainSummary

        Engine.init(seed=0)
        ts = TrainSummary(str(tmp_path), "lazy")
        opt = LocalOptimizer(_toy_model(), _toy_data(), nn.ClassNLLCriterion())
        opt.set_optim_method(SGD(learningrate=0.1))
        opt.log_every = 5
        opt.set_end_when(Trigger.max_iteration(12))
        opt.set_train_summary(ts)
        opt.optimize()
        ts.close()
        losses = ts.read_scalar("Loss")
        steps = sorted(s for s, _, _ in losses)
        assert steps == list(range(1, 13))
        # monotone-ish decrease on this separable toy: first > last
        vals = {s: v for s, v, _ in losses}
        assert vals[12] < vals[1]
        assert "loss" in opt.state and np.isfinite(opt.state["loss"])

    def test_state_loss_matches_eager(self):
        """log_every=4 and log_every=1 runs produce identical final params and
        final loss (fetch cadence must not change the math)."""
        finals = []
        for le in (1, 4):
            Engine.reset()
            Engine.init(seed=0)
            opt = LocalOptimizer(_toy_model(), _toy_data(), nn.ClassNLLCriterion())
            opt.set_optim_method(SGD(learningrate=0.1))
            opt.log_every = le
            opt.set_end_when(Trigger.max_iteration(8))
            opt.optimize()
            finals.append((opt.state["loss"],
                           [np.asarray(x) for x in
                            jax.tree_util.tree_leaves(opt.model.get_params())]))
        assert finals[0][0] == pytest.approx(finals[1][0], rel=1e-6)
        for x, y in zip(finals[0][1], finals[1][1]):
            np.testing.assert_allclose(x, y, rtol=1e-6)


class TestNamedPlateauMonitor:
    def test_monitor_by_validation_method_name(self):
        """Plateau(monitor='Loss(val)') must track the NAMED method, not whatever
        was first in the set_validation list (round-2 weak #7)."""
        Engine.init(seed=0)
        data = _toy_data()
        method = Top1Accuracy()
        # epsilon huge → every round after the first counts as "no improvement"
        sched = Plateau(monitor=method.name, factor=0.5, patience=0, mode="max",
                        epsilon=1e9)
        opt = LocalOptimizer(_toy_model(), data, nn.ClassNLLCriterion())
        opt.set_optim_method(SGD(learningrate=0.4, learningrate_schedule=sched))
        # Loss listed FIRST: positional coupling would monitor it instead
        opt.set_validation(Trigger.several_iteration(2), data,
                           [Loss(nn.ClassNLLCriterion()), method])
        opt.set_end_when(Trigger.max_iteration(8))
        opt.optimize()
        assert method.name in opt.state.get("scores", {})
        # patience=0 + never-improving epsilon → LR must have decayed
        assert sched.current_lr < 0.4

    def test_unknown_monitor_name_raises(self):
        Engine.init(seed=0)
        data = _toy_data()
        sched = Plateau(monitor="NoSuchMetric", factor=0.5, patience=0)
        opt = LocalOptimizer(_toy_model(), data, nn.ClassNLLCriterion())
        opt.set_optim_method(SGD(learningrate=0.1, learningrate_schedule=sched))
        opt.set_validation(Trigger.several_iteration(2), data, [Top1Accuracy()])
        opt.set_end_when(Trigger.max_iteration(4))
        with pytest.raises(ValueError, match="NoSuchMetric"):
            opt.optimize()


class TestBatchedValidationFetch:
    def test_validation_results_unchanged(self):
        """Chunked device_get path must produce the same validation metrics as a
        reference per-batch evaluation."""
        Engine.init(seed=0)
        data = _toy_data(n=128, batch=8)  # 16 batches → crosses the chunk boundary
        model = _toy_model()
        opt = LocalOptimizer(model, data, nn.ClassNLLCriterion())
        opt.set_optim_method(SGD(learningrate=0.1))
        opt.set_validation(Trigger.several_iteration(4), data,
                           [Top1Accuracy(), Loss(nn.ClassNLLCriterion())])
        opt.set_end_when(Trigger.max_iteration(4))
        opt.optimize()
        scores = opt.state["scores"]
        assert "Top1Accuracy" in scores

        # oracle: direct forward over the same data
        from bigdl_tpu.optim.evaluator import cached_forward_jit
        fwd = cached_forward_jit(model)
        params, mstate = model.get_params(), model.get_state()
        correct = total = 0
        for b in data.data(train=False):
            out = np.asarray(fwd(params, mstate, jnp.asarray(b.input)))
            pred = out[: b.valid].argmax(axis=1)
            correct += (pred == np.asarray(b.target)[: b.valid]).sum()
            total += b.valid
        assert scores["Top1Accuracy"] == pytest.approx(correct / total, abs=1e-6)


class TestDeviceBatchCache:
    """Device-side batch cache (cached-RDD analog): in-memory datasets place
    each distinct MiniBatch once; streamed/transformed pipelines never cache."""

    def _mk(self, n=4):
        import numpy as np
        from bigdl_tpu import nn
        from bigdl_tpu.dataset.dataset import DataSet
        from bigdl_tpu.dataset.sample import MiniBatch
        from bigdl_tpu.optim import SGD
        from bigdl_tpu.optim.optimizer import LocalOptimizer
        rng = np.random.default_rng(0)
        batches = [MiniBatch(rng.normal(size=(8, 6)).astype(np.float32),
                             rng.integers(0, 3, size=(8,)).astype(np.int32))
                   for _ in range(n)]
        model = nn.Sequential().add(nn.Linear(6, 3)).add(nn.LogSoftMax())
        ds = DataSet.array(batches)
        opt = LocalOptimizer(model, ds, nn.ClassNLLCriterion())
        opt.set_optim_method(SGD(learningrate=0.1))
        return opt, batches

    def test_cache_hits_across_epochs(self):
        from bigdl_tpu.optim.trigger import Trigger
        opt, batches = self._mk(4)
        opt.set_end_when(Trigger.max_iteration(12))  # 3 epochs over 4 batches
        opt.optimize()
        assert opt._device_batch_cache is not None
        assert len(opt._device_batch_cache) == 4  # one entry per distinct batch
        placed_first = opt._device_batch_cache[id(batches[0])][1]
        assert opt._put_batch(batches[0]) is placed_first  # identity reuse

    def test_cache_disabled_by_env(self, monkeypatch):
        from bigdl_tpu.optim.trigger import Trigger
        monkeypatch.setenv("BIGDL_DEVICE_CACHE", "0")
        opt, _ = self._mk(2)
        opt.set_end_when(Trigger.max_iteration(2))
        opt.optimize()
        assert opt._device_batch_cache is None

    def test_cache_respects_budget(self):
        opt, _ = self._mk(2)
        opt.device_cache_mb = 1e-9  # dataset exceeds the budget
        opt._setup_device_cache()
        assert opt._device_batch_cache is None

    def test_transformed_dataset_not_cached(self):
        from bigdl_tpu.dataset.transformer import Transformer
        from bigdl_tpu.optim.trigger import Trigger

        class Ident(Transformer):
            def __call__(self, it):
                return iter(list(it))

        opt, _ = self._mk(2)
        opt.dataset = opt.dataset >> Ident()
        opt.set_end_when(Trigger.max_iteration(2))
        opt.optimize()
        assert opt._device_batch_cache is None


class TestDeviceCacheDtypeInvalidation:
    def test_dtype_switch_drops_cache(self):
        import jax.numpy as jnp
        import numpy as np
        from bigdl_tpu import nn
        from bigdl_tpu.dataset.dataset import DataSet
        from bigdl_tpu.dataset.sample import MiniBatch
        from bigdl_tpu.optim import SGD
        from bigdl_tpu.optim.optimizer import LocalOptimizer
        from bigdl_tpu.optim.trigger import Trigger
        from bigdl_tpu.utils.engine import Engine

        rng = np.random.default_rng(0)
        batches = [MiniBatch(rng.normal(size=(4, 5)).astype(np.float32),
                             rng.integers(0, 2, size=(4,)).astype(np.int32))]
        model = nn.Sequential().add(nn.Linear(5, 2)).add(nn.LogSoftMax())
        Engine.reset()
        Engine.init(compute_dtype=jnp.bfloat16)
        try:
            opt = LocalOptimizer(model, DataSet.array(batches),
                                 nn.ClassNLLCriterion())
            opt.set_optim_method(SGD(learningrate=0.1))
            opt.set_end_when(Trigger.max_iteration(1))
            opt.optimize()
            assert opt._device_batch_cache
            placed = next(iter(opt._device_batch_cache.values()))[1]
            assert placed[0].dtype == jnp.bfloat16  # cast pre-transfer
            # switch precision: the bf16-truncated cache must NOT survive
            Engine.reset()
            Engine.init(compute_dtype=jnp.float32)
            opt._step_cache = None
            opt.set_end_when(Trigger.max_iteration(2))
            opt.optimize()
            placed = next(iter(opt._device_batch_cache.values()))[1]
            assert placed[0].dtype == jnp.float32
        finally:
            Engine.reset()
            Engine.init()
