"""SSD zoo model: graph shape contract, layout equivalence, serialization,
and a short must-learn training run."""

import numpy as np
import jax.numpy as jnp
import pytest

from bigdl_tpu import nn
from bigdl_tpu.models.ssd import SSD, PermuteFlatten, detector


def test_wire_format_shapes():
    n_cls, img = 3, 64
    m = SSD(n_cls, img_size=img)
    m.evaluate()
    x = jnp.zeros((2, 3, img, img), jnp.float32)
    out = m.forward(x)
    loc, conf, priors = out.values()
    p8, p16 = (img // 8) ** 2, (img // 16) ** 2
    p = p8 + p16
    assert loc.shape == (2, p * 4)
    assert conf.shape == (2, p * n_cls)
    assert priors.shape == (1, 2, p * 4)
    # prior boxes are plausible normalized corners
    pb = np.asarray(priors)[0, 0].reshape(-1, 4)
    assert (pb[:, 2] > pb[:, 0]).all() and (pb[:, 3] > pb[:, 1]).all()


def test_aspect_ratio_head_sizing():
    m = SSD(2, img_size=64, aspect_ratios=[2.0])
    x = jnp.zeros((1, 3, 64, 64), jnp.float32)
    m.evaluate()
    loc, conf, priors = m.forward(x).values()
    # ar 2 + flip -> 3 priors/cell on both scales
    p = 3 * ((64 // 8) ** 2 + (64 // 16) ** 2)
    assert loc.shape == (1, p * 4)
    assert priors.shape == (1, 2, p * 4)


def test_permute_flatten_matches_prior_order():
    # channel-last flatten: position blocks contiguous, channels innermost
    x = jnp.asarray(np.arange(2 * 4 * 2 * 3).reshape(2, 4, 2, 3)
                    .astype(np.float32))
    out = np.asarray(PermuteFlatten().forward(x))
    want = np.asarray(x).transpose(0, 2, 3, 1).reshape(2, -1)
    np.testing.assert_array_equal(out, want)


def test_detection_output_consumes_model_wire():
    m = SSD(3, img_size=32)
    serve = detector(m, 3, keep_topk=4)
    det = serve(jnp.zeros((2, 3, 32, 32), jnp.float32))
    assert np.asarray(det).shape == (2, 4, 6)


def test_serializer_roundtrip():
    import os
    import tempfile
    m = SSD(2, img_size=32)
    m.evaluate()
    x = jnp.asarray(np.random.RandomState(0).rand(1, 3, 32, 32)
                    .astype(np.float32))
    want = [np.asarray(v) for v in m.forward(x).values()]
    with tempfile.TemporaryDirectory() as d:
        path = os.path.join(d, "ssd.bigdl")
        m.save_module(path)
        m2 = nn.AbstractModule.load(path)
    m2.evaluate()
    got = [np.asarray(v) for v in m2.forward(x).values()]
    for a, b in zip(got, want):
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)


def test_train_main_learns():
    from bigdl_tpu.models.ssd.train import main
    iou = main(["--max-epoch", "12", "--n-train", "128", "--img-size", "32",
                "--batch-size", "16"])
    assert iou > 0.3, f"SSD train main failed to localize (mean IoU {iou})"
