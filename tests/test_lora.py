"""LoRA: identity-at-init, frozen base (byte-identical through training),
adapter-only gradients, merge equality, Graph surgery, serializer round
trip, and a fine-tune that actually learns."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from bigdl_tpu import Engine, nn
from bigdl_tpu.utils.random_generator import RandomGenerator


def _mlp(seed=31):
    RandomGenerator.set_seed(seed)
    m = nn.Sequential()
    m.add(nn.Linear(8, 16))
    m.add(nn.ReLU())
    m.add(nn.Linear(16, 4))
    m.add(nn.LogSoftMax())
    return m


def test_adapter_is_identity_at_init():
    m = _mlp()
    x = jnp.asarray(np.random.RandomState(0).randn(3, 8).astype(np.float32))
    m.evaluate()
    want = np.asarray(m.forward(x))
    n = nn.apply_lora(m, rank=2)
    assert n == 2
    m.evaluate()
    np.testing.assert_allclose(np.asarray(m.forward(x)), want, rtol=1e-6)


def test_only_adapter_gets_gradients_and_base_stays_frozen():
    from bigdl_tpu.dataset import DataSet
    from bigdl_tpu.dataset.sample import MiniBatch
    from bigdl_tpu.optim import LocalOptimizer, SGD, Trigger

    Engine.reset()
    Engine.init(seed=0)
    m = _mlp()
    nn.apply_lora(m, rank=2)
    flat = jax.tree_util.tree_leaves_with_path(m.get_params())
    before = {jax.tree_util.keystr(k): np.asarray(v).copy() for k, v in flat}

    rng = np.random.default_rng(1)
    data = DataSet.array([
        MiniBatch(rng.normal(size=(16, 8)).astype(np.float32),
                  rng.integers(0, 4, size=(16,)).astype(np.int32))
        for _ in range(2)])
    opt = (LocalOptimizer(m, data, nn.ClassNLLCriterion())
           .set_optim_method(SGD(learningrate=0.5))
           .set_end_when(Trigger.max_iteration(4)))
    opt.optimize()
    after = {jax.tree_util.keystr(k): np.asarray(v)
             for k, v in jax.tree_util.tree_leaves_with_path(m.get_params())}
    for k in before:
        if "lora" not in k:   # base weight/bias: byte-identical through training
            np.testing.assert_array_equal(before[k], after[k], err_msg=k)
    moved = [k for k in before
             if "lora" in k and not np.array_equal(before[k], after[k])]
    assert moved, "no adapter leaf changed during training"


def test_lora_finetune_learns_then_merges():
    from bigdl_tpu.dataset import DataSet
    from bigdl_tpu.dataset.sample import MiniBatch
    from bigdl_tpu.optim import Adam, LocalOptimizer, Trigger

    Engine.reset()
    Engine.init(seed=0)
    m = _mlp(seed=33)
    nn.apply_lora(m, rank=4)
    rng = np.random.default_rng(2)
    xs = rng.normal(size=(128, 8)).astype(np.float32)
    ys = (xs[:, 0] > 0).astype(np.int32) + 2 * (xs[:, 1] > 0).astype(np.int32)
    data = DataSet.array([MiniBatch(xs[i:i + 16], ys[i:i + 16])
                          for i in range(0, 128, 16)])
    opt = (LocalOptimizer(m, data, nn.ClassNLLCriterion())
           .set_optim_method(Adam(learningrate=0.05))
           .set_end_when(Trigger.max_epoch(30)))
    opt.optimize()
    m.evaluate()
    acc = (np.asarray(m.forward(jnp.asarray(xs))).argmax(-1) == ys).mean()
    assert acc > 0.9, f"LoRA fine-tune failed to learn (acc={acc})"

    # merge: plain Linears, same outputs
    want = np.asarray(m.forward(jnp.asarray(xs[:8])))
    n = nn.merge_lora(m)
    assert n == 2
    assert all(type(c) is not nn.LoRALinear for c in m.modules)
    m.evaluate()
    np.testing.assert_allclose(np.asarray(m.forward(jnp.asarray(xs[:8]))),
                               want, rtol=1e-4, atol=1e-5)


def test_apply_lora_reaches_graph_nodes():
    inp = nn.Input()
    h = nn.Linear(6, 5).inputs(inp)
    r = nn.ReLU().inputs(h)
    out = nn.Linear(5, 3).inputs(r)
    g = nn.Graph([inp], [out])
    x = jnp.asarray(np.random.RandomState(3).randn(2, 6).astype(np.float32))
    g.evaluate()
    want = np.asarray(g.forward(x))
    assert nn.apply_lora(g, rank=2) == 2
    g.evaluate()
    np.testing.assert_allclose(np.asarray(g.forward(x)), want, rtol=1e-6)


def test_timedistributed_linear_adapted():
    m = nn.Sequential().add(nn.TimeDistributed(nn.Linear(4, 4)))
    assert nn.apply_lora(m, rank=2) == 1
    x = jnp.asarray(np.random.RandomState(4).randn(2, 3, 4).astype(np.float32))
    assert m.forward(x).shape == (2, 3, 4)


def test_no_linear_raises_without_mutating():
    m = nn.Sequential().add(nn.SpatialConvolution(1, 2, 3, 3))
    with pytest.raises(ValueError, match="no nn.Linear"):
        nn.apply_lora(m, rank=2)
    assert not m.modules[0].is_frozen(), "failed apply_lora mutated the model"


def test_bare_roots_rejected_loudly():
    with pytest.raises(ValueError, match="from_linear"):
        nn.apply_lora(nn.Linear(4, 4), rank=2)
    lora = nn.LoRALinear.from_linear(nn.Linear(4, 4), rank=2)
    with pytest.raises(ValueError, match="to_linear"):
        nn.merge_lora(lora)


def test_frozen_flag_survives_archive_roundtrip():
    import os
    import tempfile
    m = nn.Sequential()
    m.add(nn.SpatialConvolution(1, 2, 3, 3))
    m.add(nn.Flatten() if hasattr(nn, "Flatten") else nn.Identity())
    m.add(nn.Linear(2 * 6 * 6, 3))
    nn.apply_lora(m, rank=2)          # freezes the conv too
    assert m.modules[0].is_frozen()
    with tempfile.TemporaryDirectory() as d:
        p = os.path.join(d, "f.bigdl")
        m.save_module(p)
        m2 = nn.AbstractModule.load(p)
    assert m2.modules[0].is_frozen(), \
        "frozen-trunk contract lost in the portable archive round trip"


def test_frozen_backward_is_dead_coded():
    """freeze()/LoRA must SKIP the frozen backward, not compute-and-zero it:
    the compiled step of a frozen-trunk model has measurably fewer XLA flops
    than the fully-trainable step."""
    from bigdl_tpu.dataset import DataSet
    from bigdl_tpu.dataset.sample import MiniBatch
    from bigdl_tpu.optim import LocalOptimizer, SGD

    def step_flops(freeze_trunk):
        Engine.reset()
        Engine.init(seed=0)
        RandomGenerator.set_seed(40)
        m = nn.Sequential()
        m.add(nn.SpatialConvolution(3, 16, 3, 3, pad_w=1, pad_h=1))
        m.add(nn.ReLU())
        m.add(nn.SpatialConvolution(16, 16, 3, 3, pad_w=1, pad_h=1))
        m.add(nn.ReLU())
        m.add(nn.Reshape([16 * 16 * 16]))
        m.add(nn.Linear(16 * 16 * 16, 5))
        m.add(nn.LogSoftMax())
        if freeze_trunk:
            for c in m.modules[:4]:
                c.freeze()
        rng = np.random.default_rng(0)
        data = DataSet.array([MiniBatch(
            rng.normal(size=(8, 3, 16, 16)).astype(np.float32),
            rng.integers(0, 5, size=(8,)).astype(np.int32))])
        opt = LocalOptimizer(m, data, nn.ClassNLLCriterion()) \
            .set_optim_method(SGD(learningrate=0.1))
        step = opt._compile_step()
        p = m.get_params()
        lowered = step.lower(p, m.get_state(),
                             opt.optim_method.init_state(p),
                             jnp.asarray(0, jnp.int32),
                             jnp.zeros((8, 3, 16, 16), jnp.float32),
                             jnp.zeros((8,), jnp.int32), None)
        ca = lowered.compile().cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0]
        return float(ca["flops"])

    full = step_flops(False)
    frozen = step_flops(True)
    assert frozen < 0.8 * full, (
        f"frozen-trunk step flops {frozen} not meaningfully below full "
        f"{full} — the frozen backward is still being computed")


def test_serializer_roundtrip_lora():
    import os
    import tempfile
    m = _mlp(seed=35)
    nn.apply_lora(m, rank=2)
    m.evaluate()
    x = jnp.asarray(np.random.RandomState(5).randn(2, 8).astype(np.float32))
    want = np.asarray(m.forward(x))
    with tempfile.TemporaryDirectory() as d:
        p = os.path.join(d, "lora.bigdl")
        m.save_module(p)
        m2 = nn.AbstractModule.load(p)
    m2.evaluate()
    np.testing.assert_allclose(np.asarray(m2.forward(x)), want, rtol=1e-5)


def test_attention_lora_transformer_finetune():
    """LoRA on a TransformerLM: attention projections + MLP Linears adapt,
    all bases stay byte-frozen, adapters learn, merge == adapted."""
    from bigdl_tpu.dataset import DataSet, SampleToMiniBatch
    from bigdl_tpu.dataset.sample import Sample
    from bigdl_tpu.models.transformerlm import TransformerLM, lm_criterion
    from bigdl_tpu.optim import Adam, LocalOptimizer, Trigger

    Engine.reset()
    Engine.init(seed=0)
    rng = np.random.RandomState(44)
    v, t = 17, 8
    seqs = np.zeros((64, t + 1), np.int64)
    seqs[:, 0] = rng.randint(0, v, 64)
    for i in range(t):
        seqs[:, i + 1] = (seqs[:, i] * 3 + 1) % v
    model = TransformerLM(v, embed_dim=32, num_heads=4, num_layers=1,
                          max_len=t)
    n = nn.apply_lora(model, rank=4)
    assert n >= 4   # attention + 2 mlp linears + decoder head

    flat = jax.tree_util.tree_leaves_with_path(model.get_params())
    before = {jax.tree_util.keystr(k): np.asarray(x).copy() for k, x in flat}
    data = DataSet.array([Sample(s[:-1].astype(np.int32),
                                 s[1:].astype(np.int32)) for s in seqs]) \
        >> SampleToMiniBatch(16)
    opt = (LocalOptimizer(model, data, lm_criterion())
           .set_optim_method(Adam(learningrate=0.02))
           .set_end_when(Trigger.max_epoch(40)))
    opt.optimize()
    after = {jax.tree_util.keystr(k): np.asarray(x)
             for k, x in jax.tree_util.tree_leaves_with_path(model.get_params())}
    for k in before:
        if "lora" not in k:
            np.testing.assert_array_equal(before[k], after[k], err_msg=k)
    model.evaluate()
    x = jnp.asarray(seqs[:16, :-1].astype(np.int32))
    acc = (np.asarray(model.forward(x)).argmax(-1) == seqs[:16, 1:]).mean()
    assert acc > 0.85, f"attention-LoRA fine-tune failed (acc={acc})"

    want = np.asarray(model.forward(x))
    assert nn.merge_lora(model) == n
    model.evaluate()
    np.testing.assert_allclose(np.asarray(model.forward(x)), want,
                               rtol=1e-4, atol=1e-5)


def test_attention_lora_identity_at_init_and_serializes():
    import os
    import tempfile
    RandomGenerator.set_seed(45)
    m = nn.MultiHeadAttention(16, 4, causal=True)
    x = jnp.asarray(np.random.RandomState(6).randn(2, 5, 16).astype(np.float32))
    m.evaluate()
    want = np.asarray(m.forward(x))
    m.add_lora(4)
    m._apply_cache = {}
    m.evaluate()
    np.testing.assert_allclose(np.asarray(m.forward(x)), want, rtol=1e-6)
    with tempfile.TemporaryDirectory() as d:
        p = os.path.join(d, "a.bigdl")
        m.save_module(p)
        m2 = nn.AbstractModule.load(p)
    assert m2.lora_rank == 4
    m2.evaluate()
    np.testing.assert_allclose(np.asarray(m2.forward(x)), want, rtol=1e-6)
    m2.merge_lora()
    assert not any(k.startswith("lora_") for k in m2.get_params())
    m2._apply_cache = {}
    np.testing.assert_allclose(np.asarray(m2.forward(x)), want, rtol=1e-5)


def test_attention_lora_survives_reset_and_root_adapt():
    RandomGenerator.set_seed(46)
    m = nn.MultiHeadAttention(16, 4, causal=True)
    assert nn.apply_lora(m, rank=2) == 1        # bare-MHA root adapts in place
    assert m.lora_rank == 2
    m.reset()                                   # re-randomise keeps adapters
    assert any(k.startswith("lora_") for k in m.get_params())
    x = jnp.asarray(np.random.RandomState(9).randn(1, 4, 16).astype(np.float32))
    m.evaluate()
    assert np.isfinite(np.asarray(m.forward(x))).all()
    # merge refreshes grads: parameters()/grads stay aligned
    assert nn.merge_lora(m) == 1
    assert set(m.get_grads()) == set(m.get_params())
    with pytest.raises(ValueError, match="rank"):
        nn.MultiHeadAttention(16, 4).add_lora(0)


def test_lora_composes_with_distri_fsdp():
    """Adapters train under DistriOptimizer fsdp sharding on the mesh; bases
    stay byte-frozen across the sharded update."""
    from bigdl_tpu.dataset import DataSet
    from bigdl_tpu.dataset.sample import MiniBatch
    from bigdl_tpu.optim import SGD, Trigger
    from bigdl_tpu.optim.distri_optimizer import DistriOptimizer

    Engine.reset()
    Engine.init(seed=0)
    m = _mlp(seed=37)
    nn.apply_lora(m, rank=2)
    flat = jax.tree_util.tree_leaves_with_path(m.get_params())
    before = {jax.tree_util.keystr(k): np.asarray(v).copy() for k, v in flat}
    rng = np.random.default_rng(3)
    data = DataSet.array([
        MiniBatch(rng.normal(size=(16, 8)).astype(np.float32),
                  rng.integers(0, 4, size=(16,)).astype(np.int32))
        for _ in range(3)], distributed=True)
    opt = (DistriOptimizer(m, data, nn.ClassNLLCriterion(),
                           parameter_sync="fsdp")
           .set_optim_method(SGD(learningrate=0.3))
           .set_end_when(Trigger.max_iteration(5)))
    opt.optimize()
    after = {jax.tree_util.keystr(k): np.asarray(v)
             for k, v in jax.tree_util.tree_leaves_with_path(m.get_params())}
    for k in before:
        if "lora" not in k:
            np.testing.assert_array_equal(before[k], after[k], err_msg=k)
    assert any("lora" in k and not np.array_equal(before[k], after[k])
               for k in before)


def test_lora_swapped_wrapper_saves_after_training():
    """Regression: TimeDistributed records its child in _init_args — after
    apply_lora swaps it, save_module must encode the NEW child (the stale one
    holds jit-donated, deleted arrays)."""
    import os
    import tempfile
    from bigdl_tpu.dataset import DataSet
    from bigdl_tpu.dataset.sample import MiniBatch
    from bigdl_tpu.models.transformerlm import TransformerLM, lm_criterion
    from bigdl_tpu.optim import Adam, LocalOptimizer, Trigger

    Engine.reset()
    Engine.init(seed=0)
    m = TransformerLM(48, embed_dim=32, num_heads=4, num_layers=1, max_len=16,
                      position="rope")
    nn.apply_lora(m, rank=4)
    rng = np.random.default_rng(0)
    data = DataSet.array([MiniBatch(
        rng.integers(0, 48, (8, 16)).astype(np.int32),
        rng.integers(0, 48, (8, 16)).astype(np.int32))])
    opt = (LocalOptimizer(m, data, lm_criterion())
           .set_optim_method(Adam(learningrate=1e-3))
           .set_end_when(Trigger.max_iteration(3)))
    opt.optimize()
    with tempfile.TemporaryDirectory() as d:
        p = os.path.join(d, "tuned.bigdl")
        m.save_module(p)          # raised RuntimeError before the fix
        m2 = nn.AbstractModule.load(p)
    m2.evaluate()
    x = jnp.asarray(rng.integers(0, 48, (2, 16)).astype(np.int32))
    m.evaluate()
    np.testing.assert_allclose(np.asarray(m2.forward(x)),
                               np.asarray(m.forward(x)), rtol=1e-5, atol=1e-6)
