"""Cross-replica sync-BatchNorm (SURVEY.md §7.4, round-4 verdict item 6).

``SpatialBatchNormalization(sync=True)`` pmean's the batch moments over the
named mesh axis inside a ``shard_map`` body, so data-parallel shards normalise
with GLOBAL-batch statistics. Done-criterion test: sync stats on a dp-split
batch equal single-device stats on the same global batch; sync=False (default)
keeps per-shard statistics (reference per-worker BN behavior).
"""

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from bigdl_tpu import nn


def _mesh():
    return Mesh(np.asarray(jax.devices()[:4]), ("data",))


def _shard_apply(module, x, training=True):
    params, state = module.get_params(), module.get_state()

    def body(p, s, xx):
        out, new_s = module.apply(p, s, xx, training=training, rng=None)
        return out, new_s

    fn = jax.shard_map(body, mesh=_mesh(),
                       in_specs=(P(), P(), P("data")),
                       out_specs=(P("data"), P()))
    return fn(params, state, jnp.asarray(x))


def test_sync_bn_matches_global_batch_stats():
    rng = np.random.default_rng(0)
    x = (rng.normal(size=(8, 5, 6, 6)) * 2.0 + 3.0).astype(np.float32)

    ref = nn.SpatialBatchNormalization(5)
    ref_out, ref_state = ref.apply(ref.get_params(), ref.get_state(),
                                   jnp.asarray(x), training=True)

    sync = nn.SpatialBatchNormalization(5, sync=True)
    sync.set_params(ref.get_params())
    out, new_state = _shard_apply(sync, x)

    assert np.allclose(ref_out, out, atol=1e-5)
    assert np.allclose(ref_state["running_mean"], new_state["running_mean"],
                       atol=1e-6)
    # unbiased correction uses the GLOBAL n (per-shard n would inflate var)
    assert np.allclose(ref_state["running_var"], new_state["running_var"],
                       atol=1e-5)


def test_default_bn_is_per_shard():
    rng = np.random.default_rng(1)
    # make shards statistically different so per-shard != global
    x = rng.normal(size=(8, 3, 4, 4)).astype(np.float32)
    x[:4] += 10.0

    ref = nn.SpatialBatchNormalization(3)
    _, ref_state = ref.apply(ref.get_params(), ref.get_state(),
                             jnp.asarray(x), training=True)

    per_shard = nn.SpatialBatchNormalization(3)
    per_shard.set_params(ref.get_params())
    params, state = per_shard.get_params(), per_shard.get_state()

    def body(p, s, xx):
        _, new_s = per_shard.apply(p, s, xx, training=True, rng=None)
        # stats are shard-varying here — stack them for inspection
        return new_s["running_var"][None]

    fn = jax.shard_map(body, mesh=_mesh(),
                       in_specs=(P(), P(), P("data")), out_specs=P("data"))
    shard_vars = np.asarray(fn(params, state, jnp.asarray(x)))
    assert shard_vars.shape[0] == 4
    # per-shard running_var misses the cross-shard mean offset entirely
    for i in range(4):
        assert not np.allclose(ref_state["running_var"], shard_vars[i],
                               rtol=0.2)


def test_sync_bn_trains_through_grad():
    """pmean participates in autodiff: grads flow and match the single-device
    global-batch gradient."""
    rng = np.random.default_rng(2)
    x = rng.normal(size=(8, 4, 3, 3)).astype(np.float32)

    ref = nn.SpatialBatchNormalization(4)
    params = ref.get_params()
    state = ref.get_state()

    def ref_loss(p):
        out, _ = ref.apply(p, state, jnp.asarray(x), training=True)
        return jnp.sum(out ** 2)

    g_ref = jax.grad(ref_loss)(params)

    sync = nn.SpatialBatchNormalization(4, sync=True)

    def sharded_loss(p):
        def body(pp, xx):
            out, _ = sync.apply(pp, state, xx, training=True)
            return jax.lax.psum(jnp.sum(out ** 2), "data")

        fn = jax.shard_map(body, mesh=_mesh(),
                           in_specs=(P(), P("data")), out_specs=P())
        return fn(p, jnp.asarray(x))

    g_sync = jax.grad(sharded_loss)(params)
    for k in g_ref:
        assert np.allclose(g_ref[k], g_sync[k], atol=1e-4), k
