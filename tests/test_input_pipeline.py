"""Input pipeline: PrefetchingFeed, on-disk ImageFolder source, per-phase
metrics, and the jax.profiler capture hook."""

import os
import time

import numpy as np
import pytest

from bigdl_tpu.dataset.prefetch import PrefetchingFeed


class TestPrefetchingFeed:
    def test_yields_all_in_order(self):
        items = list(range(20))
        feed = PrefetchingFeed(lambda: iter(items), lambda b: b * 10, depth=3)
        got = list(feed)
        assert got == [(i, i * 10) for i in items]

    def test_depth_zero_synchronous(self):
        feed = PrefetchingFeed(lambda: iter([1, 2]), lambda b: b, depth=0)
        assert list(feed) == [(1, 1), (2, 2)]

    def test_overlaps_producer_with_consumer(self):
        # producer "assembly" takes 20ms/batch; consumer "compute" 20ms/batch.
        # serial = ~n*40ms, overlapped = ~n*20ms. assert well under serial.
        n = 8

        def slow_iter():
            for i in range(n):
                time.sleep(0.02)
                yield i

        feed = PrefetchingFeed(lambda: slow_iter(), lambda b: b, depth=2)
        t0 = time.perf_counter()
        for _item in feed:
            time.sleep(0.02)
        dt = time.perf_counter() - t0
        assert dt < n * 0.04 * 0.85, f"no overlap: {dt:.3f}s"

    def test_producer_exception_surfaces(self):
        def bad_iter():
            yield 1
            raise ValueError("boom")

        feed = PrefetchingFeed(lambda: bad_iter(), lambda b: b, depth=2)
        with pytest.raises(ValueError, match="boom"):
            list(feed)

    def test_early_break_stops_producer(self):
        produced = []

        def counted():
            for i in range(10_000):
                produced.append(i)
                yield i

        feed = PrefetchingFeed(lambda: counted(), lambda b: b, depth=2)
        for item, _ in feed:
            if item == 3:
                break
        feed.close()
        n_after_close = len(produced)
        time.sleep(0.2)
        assert len(produced) == n_after_close  # producer actually stopped
        assert n_after_close < 100


class TestImageFolder:
    @pytest.fixture()
    def folder(self, tmp_path):
        from bigdl_tpu.dataset.image_folder import write_synthetic_image_folder
        return write_synthetic_image_folder(str(tmp_path), n_classes=3,
                                            n_per_class=4, size=40)

    def test_scan_and_stream(self, folder):
        from bigdl_tpu.dataset.dataset import DataSet
        ds = DataSet.image_folder(folder, num_workers=2)
        assert ds.size() == 12
        feats = list(ds.data(train=False))
        assert len(feats) == 12
        assert feats[0].image.shape == (40, 40, 3)
        labels = sorted({f["label"] for f in feats})
        assert labels == [0, 1, 2]

    def test_one_based_labels(self, folder):
        from bigdl_tpu.dataset.dataset import DataSet
        ds = DataSet.image_folder(folder, one_based=True)
        labels = sorted({f["label"] for f in ds.data(train=False)})
        assert labels == [1, 2, 3]

    def test_shuffle_is_seeded(self, folder):
        from bigdl_tpu.dataset.dataset import DataSet
        from bigdl_tpu.utils.random_generator import RandomGenerator

        ds = DataSet.image_folder(folder)
        RandomGenerator.set_seed(5)
        ds.shuffle()
        order1 = list(ds._order)
        ds2 = DataSet.image_folder(folder)
        RandomGenerator.set_seed(5)
        ds2.shuffle()
        assert list(ds2._order) == order1

    def test_full_pipeline_to_minibatch(self, folder):
        from bigdl_tpu.dataset.dataset import DataSet
        from bigdl_tpu.dataset.sample import SampleToMiniBatch
        from bigdl_tpu.transform.vision.image import (
            CenterCrop, ChannelNormalize, ImageFrameToSample, MatToTensor,
        )

        ds = (DataSet.image_folder(folder)
              >> CenterCrop(32, 32)
              >> ChannelNormalize((120, 120, 120), (60, 60, 60))
              >> MatToTensor()
              >> ImageFrameToSample()
              >> SampleToMiniBatch(4))
        batches = list(ds.data(train=False))
        assert len(batches) == 3
        assert batches[0].input.shape == (4, 3, 32, 32)
        assert batches[0].target.shape == (4,)

    def test_imagenet_main_trains_from_folder(self, tmp_path):
        """The round-1 NotImplementedError path: ResNet ImageNet main end-to-end
        from an on-disk folder (tiny synthetic stand-in)."""
        from bigdl_tpu.dataset.image_folder import write_synthetic_image_folder
        from bigdl_tpu.models.resnet import train as resnet_train

        folder = write_synthetic_image_folder(str(tmp_path), n_classes=2,
                                              n_per_class=4, size=80)
        model = resnet_train.main([
            "--dataset", "ImageNet", "--depth", "18", "--classes", "2",
            "-f", folder, "-b", "4", "--max-epoch", "1"])
        assert model is not None


class TestPhaseMetricsAndProfiler:
    def _train(self):
        import bigdl_tpu.nn as N
        from bigdl_tpu.dataset.dataset import DataSet
        from bigdl_tpu.dataset.sample import Sample, SampleToMiniBatch
        from bigdl_tpu.optim import SGD
        from bigdl_tpu.optim.optimizer import LocalOptimizer
        from bigdl_tpu.optim.trigger import Trigger

        rng = np.random.default_rng(0)
        x = rng.normal(size=(64, 8)).astype(np.float32)
        y = rng.integers(0, 3, size=(64,)).astype(np.int32)
        ds = (DataSet.array([Sample(x[i], y[i]) for i in range(64)])
              >> SampleToMiniBatch(16))
        model = (N.Sequential().add(N.Linear(8, 3)).add(N.LogSoftMax()))
        opt = LocalOptimizer(model, ds, N.ClassNLLCriterion())
        opt.set_optim_method(SGD(learningrate=0.1))
        opt.set_end_when(Trigger.max_iteration(12))
        return opt

    def test_phase_metrics_populate(self):
        opt = self._train()
        opt.sync_metrics = True
        opt.optimize()
        means = opt.metrics.summary()
        for phase in ("feed", "put_batch", "step_dispatch", "step_device",
                      "loss_fetch"):
            assert phase in means, means
            assert means[phase] >= 0.0

    def test_profiler_trace_captured(self, tmp_path):
        opt = self._train()
        trace_dir = str(tmp_path / "trace")
        opt.set_profile(trace_dir, start_iter=3, n_iters=4)
        opt.optimize()
        files = []
        for root, _dirs, names in os.walk(trace_dir):
            files += [os.path.join(root, n) for n in names]
        assert files, "no profiler trace files written"

    def test_second_optimize_reuses_compiled_step(self):
        opt = self._train()
        opt.optimize()
        first = opt._step_cache
        assert first is not None
        from bigdl_tpu.optim.trigger import Trigger
        opt.set_end_when(Trigger.max_iteration(24))
        opt.optimize()
        assert opt._step_cache is first  # no recompile for a warm continue
