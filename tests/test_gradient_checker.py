"""GradientChecker (SURVEY.md §4 GradientChecker analog): finite differences
vs jax.grad — the net that catches wrong custom VJPs."""

import numpy as np
import pytest

from bigdl_tpu import nn
from bigdl_tpu.utils.gradient_checker import GradientChecker
from bigdl_tpu.utils.random_generator import RandomGenerator


def _x(*shape, seed=0):
    return np.random.default_rng(seed).normal(size=shape).astype(np.float64)


class TestInputGradients:
    @pytest.mark.parametrize("factory,shape", [
        (lambda: nn.Linear(5, 3), (2, 5)),
        (lambda: nn.Tanh(), (2, 4)),
        (lambda: nn.Sigmoid(), (2, 4)),
        (lambda: nn.SoftPlus(), (2, 4)),
        (lambda: nn.Highway(4), (2, 4)),
        (lambda: nn.LayerNorm(6), (3, 6)),
    ])
    def test_layer(self, factory, shape):
        RandomGenerator.set_seed(0)
        checker = GradientChecker(1e-4, 1e-4)
        assert checker.check_layer(factory(), _x(*shape)), checker.last_error

    def test_custom_vjp_gradient_reversal(self):
        """GradientReversal's custom VJP must satisfy... nothing — it LIES by
        design (identity forward, reversed grad). The checker must FAIL it,
        proving it detects wrong-on-purpose VJPs."""
        checker = GradientChecker(1e-4, 1e-4)
        m = nn.GradientReversal(1.0).training()
        assert not checker.check_layer(m, _x(2, 3), training=True)

    def test_custom_vjp_flash_attention_path(self):
        """MultiHeadAttention with the flash custom VJP (reference-recompute
        backward) must agree with finite differences."""
        RandomGenerator.set_seed(0)
        # the attention softmax is a deliberate fp32 island (precision.py), so
        # finite differences bottom out around 1e-4 even under x64; impl=flash
        # puts the hand-written _fa_bwd custom VJP ON the differentiation path
        # (reference-recompute backward, exercised even off-TPU)
        checker = GradientChecker(1e-3, 2e-3)
        m = nn.MultiHeadAttention(8, 2, causal=True, attention_impl="flash")
        assert checker.check_layer(m, _x(1, 4, 8)), checker.last_error


class TestWeightGradients:
    def test_linear_weights(self):
        RandomGenerator.set_seed(0)
        checker = GradientChecker(1e-4, 1e-4)
        assert checker.check_weight(nn.Linear(4, 3), _x(2, 4)), \
            checker.last_error

    def test_conv_weights(self):
        RandomGenerator.set_seed(0)
        checker = GradientChecker(1e-4, 2e-4)
        m = nn.SpatialConvolution(2, 3, 3, 3, pad_w=1, pad_h=1)
        assert checker.check_weight(m, _x(1, 2, 4, 4)), checker.last_error
