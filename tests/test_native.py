"""Native batch-assembly library (SURVEY.md §2.4 native-component analog):
C++ pack/gather equals numpy, degrades gracefully, and feeds SampleToMiniBatch."""

import numpy as np
import pytest

from bigdl_tpu import native


def _arrs(n=8, shape=(3, 4), dtype=np.float32, seed=0):
    rng = np.random.default_rng(seed)
    return [rng.normal(size=shape).astype(dtype) for _ in range(n)]


class TestPackBatch:
    def test_matches_np_stack(self):
        arrs = _arrs()
        out = native.pack_batch(arrs)
        np.testing.assert_array_equal(out, np.stack(arrs))

    @pytest.mark.parametrize("dtype", [np.float32, np.int32, np.uint8,
                                       np.float64])
    def test_dtypes(self, dtype):
        arrs = _arrs(dtype=dtype)
        np.testing.assert_array_equal(native.pack_batch(arrs), np.stack(arrs))

    def test_large_batch_parallel_path(self):
        # > 8 MB total triggers the threaded copy in C++
        arrs = _arrs(n=64, shape=(512, 128))
        np.testing.assert_array_equal(native.pack_batch(arrs), np.stack(arrs))

    def test_non_contiguous_inputs(self):
        base = np.random.default_rng(0).normal(size=(8, 10, 6)).astype(np.float32)
        arrs = [base[i, ::2] for i in range(8)]  # strided views
        np.testing.assert_array_equal(native.pack_batch(arrs),
                                      np.stack(arrs))

    def test_scalar_elements_keep_rank(self):
        """0-d label arrays must stack to (N,), not (N, 1) (regression:
        ascontiguousarray promotes 0-d to 1-d)."""
        arrs = [np.asarray(np.float32(i)) for i in range(4)]
        out = native.pack_batch(arrs)
        assert out.shape == (4,)
        np.testing.assert_array_equal(out, np.stack(arrs))

    def test_single_element(self):
        arrs = _arrs(n=1)
        np.testing.assert_array_equal(native.pack_batch(arrs), np.stack(arrs))

    def test_disabled_fallback(self, monkeypatch):
        monkeypatch.setenv("BIGDL_NATIVE", "0")
        arrs = _arrs()
        np.testing.assert_array_equal(native.pack_batch(arrs), np.stack(arrs))


class TestGatherRows:
    def test_matches_fancy_index(self):
        src = np.random.default_rng(0).normal(size=(10, 7)).astype(np.float32)
        idx = np.asarray([3, 0, 9, 3, 5])
        np.testing.assert_array_equal(native.gather_rows(src, idx), src[idx])

    def test_bounds_checked_both_paths(self, monkeypatch):
        src = np.zeros((4, 2), np.float32)
        with pytest.raises(IndexError):
            native.gather_rows(src, np.asarray([0, 4]))
        # negative indices rejected identically with and without the lib
        with pytest.raises(IndexError):
            native.gather_rows(src, np.asarray([-1]))
        monkeypatch.setenv("BIGDL_NATIVE", "0")
        with pytest.raises(IndexError):
            native.gather_rows(src, np.asarray([-1]))


class TestPipelineIntegration:
    def test_sample_to_minibatch_uses_native(self):
        from bigdl_tpu.dataset import DataSet, SampleToMiniBatch
        from bigdl_tpu.dataset.sample import Sample

        rng = np.random.default_rng(0)
        samples = [Sample(rng.normal(size=(5,)).astype(np.float32),
                          np.int32(i % 3)) for i in range(10)]
        batches = list((DataSet.array(samples) >> SampleToMiniBatch(4))
                       .data(train=False))
        assert [b.size() for b in batches] == [4, 4, 4]
        assert batches[-1].valid == 2
        np.testing.assert_array_equal(
            batches[0].input, np.stack([s.feature[0] for s in samples[:4]]))

    def test_native_lib_actually_built(self):
        """On this image (g++ baked in) the native path must really engage —
        a silent permanent fallback would make the component fictional."""
        assert native.native_available()
