"""Volumetric (3-D) layers with torch oracles + RoiPooling (RoiAlign redesign)
with a hand numpy oracle."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
import torch
import torch.nn.functional as F

from bigdl_tpu import nn
from bigdl_tpu.utils.random_generator import RandomGenerator
from bigdl_tpu.utils.table import T


def _np(*shape, seed=0):
    return np.random.default_rng(seed).normal(size=shape).astype(np.float32)


class TestVolumetric:
    def test_conv3d_torch_oracle(self):
        RandomGenerator.set_seed(0)
        m = nn.VolumetricConvolution(2, 4, 3, 3, 3, d_t=2, pad_t=1,
                                     pad_w=1, pad_h=1).evaluate()
        x = _np(2, 2, 6, 8, 8)
        out = np.asarray(m.forward(jnp.asarray(x)))
        w = np.asarray(m.get_params()["weight"])
        b = np.asarray(m.get_params()["bias"])
        ref = F.conv3d(torch.tensor(x), torch.tensor(w), torch.tensor(b),
                       stride=(2, 1, 1), padding=1).numpy()
        np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-5)

    def test_maxpool3d_torch_oracle(self):
        m = nn.VolumetricMaxPooling(2, 2, 2).evaluate()
        x = _np(1, 3, 4, 6, 6)
        out = np.asarray(m.forward(jnp.asarray(x)))
        ref = F.max_pool3d(torch.tensor(x), 2).numpy()
        np.testing.assert_allclose(out, ref, rtol=1e-6)

    def test_avgpool3d_torch_oracle(self):
        m = nn.VolumetricAveragePooling(2, 2, 2, pad_t=1, pad_w=1,
                                        pad_h=1).evaluate()
        x = _np(1, 3, 4, 6, 6)
        out = np.asarray(m.forward(jnp.asarray(x)))
        ref = F.avg_pool3d(torch.tensor(x), 2, padding=1,
                           count_include_pad=True).numpy()
        np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-6)

    def test_conv3d_gradients(self):
        RandomGenerator.set_seed(0)
        m = nn.VolumetricConvolution(2, 3, 2, 2, 2)
        x = jnp.asarray(_np(1, 2, 4, 5, 5))
        y = m.training().forward(x)
        gi = m.backward(x, jnp.ones_like(y))
        assert gi.shape == x.shape and np.abs(np.asarray(gi)).max() > 0


def _roi_align_oracle(feats, rois, ph, pw, scale, ns, mode):
    """Direct numpy transcription of the RoiAlign spec."""
    r = len(rois)
    n, c, h, w = feats.shape
    out = np.zeros((r, c, ph, pw), np.float32)
    for ri, roi in enumerate(rois):
        b = int(roi[0])
        x1, y1, x2, y2 = [v * scale for v in roi[1:]]
        bw = max(x2 - x1, 1e-6) / pw
        bh = max(y2 - y1, 1e-6) / ph
        for i in range(ph):
            for j in range(pw):
                vals = []
                for sy in range(ns):
                    for sx in range(ns):
                        y = np.clip(y1 + i * bh + (sy + 0.5) / ns * bh, 0, h - 1)
                        x = np.clip(x1 + j * bw + (sx + 0.5) / ns * bw, 0, w - 1)
                        y0, x0 = int(np.floor(y)), int(np.floor(x))
                        y1i, x1i = min(y0 + 1, h - 1), min(x0 + 1, w - 1)
                        wy, wx = y - y0, x - x0
                        v = ((1 - wy) * (1 - wx) * feats[b, :, y0, x0]
                             + (1 - wy) * wx * feats[b, :, y0, x1i]
                             + wy * (1 - wx) * feats[b, :, y1i, x0]
                             + wy * wx * feats[b, :, y1i, x1i])
                        vals.append(v)
                vals = np.stack(vals)
                out[ri, :, i, j] = vals.mean(0) if mode == "avg" else vals.max(0)
    return out


class TestRoiPooling:
    @pytest.mark.parametrize("mode", ["avg", "max"])
    def test_matches_numpy_oracle(self, mode):
        feats = _np(2, 3, 10, 12)
        rois = np.asarray([[0, 1.0, 1.0, 8.0, 6.0],
                           [1, 0.0, 0.0, 11.0, 9.0],
                           [0, 4.0, 2.0, 6.5, 8.5]], np.float32)
        m = nn.RoiPooling(3, 4, spatial_scale=1.0, sampling_ratio=2,
                          mode=mode).evaluate()
        out = np.asarray(m.forward(T(jnp.asarray(feats), jnp.asarray(rois))))
        ref = _roi_align_oracle(feats, rois, 3, 4, 1.0, 2, mode)
        assert out.shape == (3, 3, 3, 4)
        np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-5)

    def test_spatial_scale(self):
        feats = _np(1, 2, 8, 8)
        rois = np.asarray([[0, 0.0, 0.0, 16.0, 16.0]], np.float32)
        m = nn.RoiPooling(2, 2, spatial_scale=0.5).evaluate()  # /2 → whole map
        out = np.asarray(m.forward(T(jnp.asarray(feats), jnp.asarray(rois))))
        ref = _roi_align_oracle(feats, rois, 2, 2, 0.5, 2, "avg")
        np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-5)

    def test_gradients_flow_to_features(self):
        feats = jnp.asarray(_np(1, 2, 8, 8))
        rois = jnp.asarray([[0, 1.0, 1.0, 6.0, 6.0]], jnp.float32)
        m = nn.RoiPooling(2, 2)

        def loss(f):
            out, _ = m.apply({}, {}, T(f, rois))
            return jnp.sum(out)

        g = np.asarray(jax.grad(loss)(feats))
        assert np.abs(g).sum() > 0
        # gradient confined to the roi's support (plus bilinear halo)
        assert np.abs(g[0, :, :, 7]).sum() == pytest.approx(0.0, abs=1e-6)
