"""Cluster-scope telemetry suite (`make t1-cluster-obs`): device-memory
accounting, multi-host metric aggregation, on-demand profiler capture, and
structured access logs (docs/observability.md).

The load-bearing contracts:

- Spool merge: every host spooled under ``BIGDL_OBS_SPOOL_DIR`` rides ONE
  ``/metrics`` scrape with a ``{host=}`` label, ``parse_metrics``
  round-trips every merged row, a torn/corrupt spool line is skipped (never
  fatal), and a dead host degrades to a stale-stamped ``obs_host_up 0`` row
  — the scrape itself never fails. The 2-process gloo drill proves the
  whole loop end to end, including the SIGKILL-one-host degrade.
- A scripted ``obs_spool_write`` failure flips that host to local-only
  metrics, loudly (robustness event + counter), without crashing anything.
- Device memory is absent-not-wrong: a backend without ``memory_stats()``
  yields NO ``device/hbm_*`` gauges rather than fake ones; the pressure
  event fires once per excursion; ``bigdl-tpu top`` renders ``-`` for every
  absent gauge.
- ``/profilez?seconds=N`` captures a ``jax.profiler.trace`` artifact (200),
  409s while one runs, 400s garbage, 503s a scripted capture failure — and
  keeps serving afterwards.
- Every finished serving request lands one access-log record;
  ``to_bdlrec`` re-shards the log into ``.bdlrec`` that StreamingDataSet
  replays with zero record loss and field fidelity.
"""

import argparse
import json
import os
import socket
import subprocess
import sys
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from bigdl_tpu import cli
from bigdl_tpu.dataset.streaming import StreamingDataSet
from bigdl_tpu.obs import access_log as obs_access_log
from bigdl_tpu.obs import cluster as obs_cluster
from bigdl_tpu.obs import device as obs_device
from bigdl_tpu.obs import exporter
from bigdl_tpu.obs.registry import registry as obs_registry
from bigdl_tpu.utils import faults
from bigdl_tpu.utils.robustness import events

pytestmark = pytest.mark.obs

_WORKER = os.path.join(os.path.dirname(__file__), "multihost_worker.py")


@pytest.fixture(autouse=True)
def _isolate():
    yield
    obs_access_log.reset()
    obs_cluster.reset()
    obs_device.reset()


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


# ------------------------------------------------------------- spool merge
class TestSpoolMerge:
    def test_host_lines_round_trip_through_parse_metrics(self, tmp_path,
                                                         monkeypatch):
        obs_registry.reset()
        try:
            obs_registry.counter("reqs").inc(3)
            obs_registry.gauge("train/throughput").set(123.5)
            for v in (1.0, 2.0, 9.0):
                obs_registry.histogram("lat_ms").observe(v)
            assert obs_cluster.SpoolWriter(
                str(tmp_path), host="h0", interval_s=60).write_once()
            obs_registry.gauge("train/throughput").set(77.25)
            assert obs_cluster.SpoolWriter(
                str(tmp_path), host="h1", interval_s=60).write_once()

            monkeypatch.setenv("BIGDL_OBS_SPOOL_DIR", str(tmp_path))
            monkeypatch.setenv("BIGDL_OBS_STALE_S", "3600")
            parsed = exporter.parse_metrics(exporter.render_metrics())
            assert parsed['bigdl_train_throughput{host="h0"}'] \
                == pytest.approx(123.5)
            assert parsed['bigdl_train_throughput{host="h1"}'] \
                == pytest.approx(77.25)
            assert parsed['bigdl_obs_host_up{host="h0"}'] == 1
            assert parsed['bigdl_reqs_total{host="h1"}'] == 3
            assert parsed['bigdl_lat_ms{host="h0",quantile="0.5"}'] \
                == pytest.approx(2.0)
            assert parsed['bigdl_lat_ms_count{host="h0"}'] == 3
            # the round-trip pin: EVERY merged row survives parse_metrics
            # (render once and parse THAT text — host ages tick between
            # renders, so two renders are not comparable row-for-row)
            hosts = obs_cluster.read_spools(str(tmp_path), stale_after_s=3600)
            lines = obs_cluster.render_host_lines(hosts)
            reparsed = exporter.parse_metrics("\n".join(lines))
            for line in lines:
                key, _, val = line.rpartition(" ")
                assert reparsed[key] == pytest.approx(float(val))
            assert set(reparsed) <= set(parsed)   # same keys ride /metrics
        finally:
            obs_registry.reset()

    def test_stale_stamp_corrupt_lines_and_last_valid_wins(self, tmp_path):
        snap = {"counters": {}, "histograms": {},
                "gauges": {"train/throughput": 5.0}}
        path = tmp_path / "host-dead.jsonl"
        with open(path, "wb") as f:
            f.write(obs_cluster._encode_line(
                {"host": "dead", "ts": time.time() - 999, "seq": 6,
                 "snapshot": {"counters": {}, "histograms": {},
                              "gauges": {"train/throughput": 4.0}}}))
            f.write(obs_cluster._encode_line(
                {"host": "dead", "ts": time.time() - 999, "seq": 7,
                 "snapshot": snap}))
            f.write(b'{"torn": tru')            # torn tail, no CRC footer
        # an all-garbage spool is skipped, never fatal
        (tmp_path / "host-junk.jsonl").write_bytes(b"\x00\x01 nope\n")
        hosts = obs_cluster.read_spools(str(tmp_path), stale_after_s=15)
        assert sorted(hosts) == ["dead"]
        assert hosts["dead"]["stale"] is True
        assert hosts["dead"]["seq"] == 7        # last VALID line wins
        assert hosts["dead"]["snapshot"]["gauges"]["train/throughput"] == 5.0
        assert 'bigdl_obs_host_up{host="dead"} 0' \
            in obs_cluster.render_host_lines(hosts)
        table = obs_cluster.host_table(hosts)
        assert table["dead"]["stale"] is True
        assert table["dead"]["throughput"] == 5.0

    def test_spool_write_fault_degrades_to_local_only_loudly(self, tmp_path):
        w = obs_cluster.SpoolWriter(str(tmp_path / "sp"), host="hx",
                                    interval_s=60)
        snap0 = events.snapshot()
        c0 = obs_registry.snapshot()["counters"].get(
            "obs/spool_write_failures", 0)
        with faults.inject_faults("obs_spool_write@1") as plan:
            assert w.write_once() is False
            assert plan.unfired() == []
        assert w.degraded
        assert w.write_once() is False          # local-only from now on
        assert not os.path.exists(w.path)       # nothing half-written
        assert events.deltas(snap0).get("obs_spool_degraded", 0) == 1
        assert obs_registry.snapshot()["counters"][
            "obs/spool_write_failures"] == c0 + 1
        # the process's own metrics plane is untouched: render still works
        assert "bigdl_obs_spool_write_failures_total" \
            in exporter.render_metrics()


# ------------------------------------------------- 2-process gloo drill
class TestClusterDrill:
    def test_two_host_merge_scrape_and_stale_degrade(self, tmp_path):
        """The tier-1 proof: both hosts train under jax.distributed while
        spooling; ONE scrape of process 0's /metrics carries BOTH hosts'
        train/throughput under distinct {host=} labels; SIGKILLing host 1
        stale-stamps its row without ever failing the scrape."""
        port = _free_port()
        env = dict(os.environ)
        env.pop("XLA_FLAGS", None)      # workers set their own device count
        env.pop("BIGDL_METRICS_PORT", None)  # worker 0 binds its own port
        env["BIGDL_MH_MODE"] = "obs"
        env["BIGDL_OBS_SPOOL_DIR"] = str(tmp_path / "spool")
        env["BIGDL_OBS_SPOOL_S"] = "0.3"
        env["BIGDL_OBS_STALE_S"] = "2.0"
        env["BIGDL_MH_ITERS"] = "6"

        outs = [str(tmp_path / f"worker{pid}.json") for pid in (0, 1)]
        p1 = subprocess.Popen(
            [sys.executable, _WORKER, str(port), "1", outs[1]],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=env)
        env0 = dict(env)
        env0["BIGDL_MH_PEER_PID"] = str(p1.pid)
        p0 = subprocess.Popen(
            [sys.executable, _WORKER, str(port), "0", outs[0]],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=env0)
        stdouts = {}
        for name, p in (("p0", p0), ("p1", p1)):
            try:
                stdouts[name], _ = p.communicate(timeout=240)
            except subprocess.TimeoutExpired:
                p0.kill()
                p1.kill()
                pytest.fail(f"obs drill worker {name} timed out")
        assert p0.returncode == 0, f"worker 0 failed:\n{stdouts['p0'][-3000:]}"
        # worker 1 is SIGKILLed mid-idle by worker 0 — that IS the drill
        assert p1.returncode == -9, (p1.returncode, stdouts["p1"][-2000:])

        with open(outs[1]) as f:
            pl1 = json.load(f)      # written BEFORE the kill
        assert pl1["host"] == "1"
        assert pl1["spool_writes"] >= 1
        with open(outs[0]) as f:
            pl0 = json.load(f)
        assert pl0["scrape_status"] == 200
        assert pl0["throughput_hosts"] == ["0", "1"]
        assert pl0["host_up_initial"] == {"0": 1, "1": 1}
        assert pl0["round_trip_ok"] is True
        # the degrade: host 1 stamped stale, host 0 live, scrape still 200
        assert pl0["stale_stamped"] is True
        assert pl0["scrape_status_after_kill"] == 200
        assert pl0["host0_up_after_kill"] == 1
        assert pl0["statusz_hosts"] == ["0", "1"]
        assert pl0["statusz_host1_stale"] is True
        # virtual CPU devices report no memory_stats — hbm rows are allowed
        # to be absent (absent-not-wrong), but never partial garbage
        assert set(pl0["hbm_hosts"]) <= {"0", "1"}


# ------------------------------------------------------------ device memory
class TestDeviceMemory:
    def test_sample_absent_not_wrong(self):
        obs_registry.reset()
        try:
            out = obs_device.sample_device_memory()
            assert isinstance(out, list)
            gauges = obs_registry.snapshot()["gauges"]
            if out:     # backend reports: aggregate gauges must exist
                assert gauges["device/hbm_bytes_in_use"] \
                    == sum(e["bytes_in_use"] for e in out)
            else:       # backend silent: NO fabricated gauges
                assert "device/hbm_bytes_in_use" not in gauges
                assert "device/hbm_headroom" not in gauges
        finally:
            obs_registry.reset()

    def test_live_buffer_census_counts_held_arrays(self):
        import jax.numpy as jnp
        x = jnp.ones((128, 64), jnp.float32)
        census = obs_device.live_buffer_census(publish=False)
        assert census["count"] >= 1
        assert census["bytes"] >= 128 * 64 * 4
        assert "float32" in census["by_dtype"]
        del x

    def test_program_memory_attribution_absent_ok(self):
        import jax
        import jax.numpy as jnp
        fn = jax.jit(lambda a, b: (a @ b).sum())
        args = (jnp.ones((8, 8), jnp.float32), jnp.ones((8, 8), jnp.float32))
        pm = obs_device.program_memory(fn, *args)
        # CPU XLA may or may not expose memory_analysis(); either way the
        # call never raises and never returns fabricated fields
        assert pm is None or (
            pm and all(isinstance(v, int) and v >= 0 for v in pm.values()))

    def test_pressure_event_fires_once_per_excursion(self):
        mon = obs_device.DeviceMonitor(interval_s=60, pressure_pct=10.0)
        snap0 = events.snapshot()
        low = [{"id": 0, "headroom": 0.02}]
        mon._check_pressure(low)
        mon._check_pressure(low)                # still in the same excursion
        assert events.deltas(snap0).get("hbm_pressure", 0) == 1
        mon._check_pressure([{"id": 0, "headroom": 0.5}])   # recovers
        mon._check_pressure(low)                # new excursion
        assert events.deltas(snap0).get("hbm_pressure", 0) == 2

    def test_monitor_stats_block_shape(self):
        mon = obs_device.DeviceMonitor(interval_s=60)
        mon.poll_once()
        assert mon.polls == 1
        st = obs_device.stats()
        assert set(st) == {"devices", "live_buffers"}
        assert isinstance(st["devices"], list)
        mon.stop()

    def test_bench_device_memory_record(self):
        from bigdl_tpu import benchmark
        rec = benchmark._device_memory_record()
        assert set(rec) >= {"devices", "hbm_bytes_in_use", "hbm_peak_bytes"}
        assert isinstance(rec["devices"], list)


# --------------------------------------------------------- profiler capture
class TestProfilez:
    def test_capture_routes_and_cli(self, tmp_path, monkeypatch):
        monkeypatch.setenv("BIGDL_TRACE_DIR", str(tmp_path))
        srv = exporter.MetricsExporter(0).start()
        try:
            with urllib.request.urlopen(
                    srv.url + "/profilez?seconds=0.05", timeout=60) as r:
                assert r.status == 200
                payload = json.loads(r.read())
            assert payload["artifact"].startswith(str(tmp_path))
            assert os.path.isdir(payload["artifact"])

            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(
                    srv.url + "/profilez?seconds=nope", timeout=10)
            assert ei.value.code == 400

            monkeypatch.setattr(exporter, "_PROFILE_BUSY", True)
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(
                    srv.url + "/profilez?seconds=0.05", timeout=10)
            assert ei.value.code == 409
            monkeypatch.setattr(exporter, "_PROFILE_BUSY", False)

            with faults.inject_faults("profilez_capture@1") as plan:
                with pytest.raises(urllib.error.HTTPError) as ei:
                    urllib.request.urlopen(
                        srv.url + "/profilez?seconds=0.05", timeout=10)
                assert ei.value.code == 503
                assert plan.unfired() == []
            # the endpoint (and the process it observes) keeps serving
            with urllib.request.urlopen(srv.url + "/metrics", timeout=10) as r:
                assert r.status == 200

            # `bigdl-tpu prof` — the CLI form of the same route
            ns = argparse.Namespace(host="127.0.0.1", port=srv.port,
                                    seconds=0.05)
            assert cli._run_prof(ns) == 0
        finally:
            srv.stop()


# -------------------------------------------------------------- access log
class TestAccessLog:
    def test_rotation_and_bdlrec_replay_zero_loss(self, tmp_path,
                                                  monkeypatch):
        log_dir, out_dir = str(tmp_path / "alog"), str(tmp_path / "rec")
        monkeypatch.setenv("BIGDL_ACCESS_LOG", log_dir)
        monkeypatch.setenv("BIGDL_ACCESS_LOG_ROTATE_MB", "0.001")  # 4 KB floor
        obs_access_log.reset()
        n = 120
        for i in range(n):
            obs_access_log.log_request(
                trace_id="t%04d" % i, tenant="lm", phase="decode",
                prompt_tokens=8 + i, output_tokens=4, ttft_ms=1.5,
                e2e_ms=9.25, flops=1.0e6,
                outcome="ok" if i % 7 else "timeout")
        log = obs_access_log.from_env()
        assert log.records == n
        assert log.rotations >= 1               # the 4 KB floor forced rolls
        log.close()
        # a torn tail (crashed writer) must be skipped by the converter
        with open(os.path.join(log_dir, "access-torn.jsonl"), "wb") as f:
            f.write(b'{"trace_id": "whole", "outcome": "ok"}\n')
            f.write(b'{"trace_id": "to')
        paths, count = obs_access_log.to_bdlrec(log_dir, out_dir, shards=2)
        assert count == n + 1
        assert len(paths) == 2 and all(os.path.exists(p) for p in paths)

        ds = StreamingDataSet(paths,
                              decoder=obs_access_log.access_record_decoder,
                              shuffle_window=1, num_workers=2, cache=False)
        recs = list(ds.data(train=False))
        ds.close()
        assert len(recs) == count               # zero record loss
        by_id = {r["trace_id"]: r for r in recs}
        assert len(by_id) == count
        # field fidelity on a sampled record
        r = by_id["t0005"]
        assert r["prompt_tokens"] == 13
        assert r["output_tokens"] == 4
        assert r["ttft_ms"] == 1.5
        assert r["e2e_ms"] == 9.25
        assert r["flops"] == 1.0e6
        assert r["outcome"] == "ok"
        assert by_id["t0007"]["outcome"] == "timeout"
        assert by_id["whole"]["outcome"] == "ok"   # the loose hand-written rec
        for rec in recs:
            if rec["trace_id"] != "whole":   # log_request pads FIELDS; the
                assert set(obs_access_log.FIELDS) <= set(rec)  # raw line not

    def test_write_failure_disables_loudly_never_raises(self, tmp_path):
        target = tmp_path / "ro"
        log = obs_access_log.AccessLog(str(target))
        log.log(trace_id="a", outcome="ok")
        assert log.records == 1
        # yank the file out from under the writer: closed handle → write fails
        log._f.close()
        log.log(trace_id="b", outcome="ok")     # must not raise
        assert log.disabled
        log.log(trace_id="c", outcome="ok")     # no-op once disabled
        assert log.records == 1

    def test_unset_env_allocates_nothing(self, monkeypatch):
        monkeypatch.delenv("BIGDL_ACCESS_LOG", raising=False)
        obs_access_log.reset()
        assert obs_access_log.from_env() is None
        obs_access_log.log_request(trace_id="x", outcome="ok")  # free no-op

    def test_engine_completion_paths_write_records(self, tmp_path,
                                                   monkeypatch):
        """Every finished request — completed AND timed out — lands one
        record with the pinned fields, via the real engine paths."""
        from bigdl_tpu.models.transformerlm import TransformerLM
        from bigdl_tpu.serving import ServingEngine

        monkeypatch.setenv("BIGDL_ACCESS_LOG", str(tmp_path / "alog"))
        obs_access_log.reset()
        lm = TransformerLM(50, embed_dim=16, num_heads=2, num_layers=1,
                           max_len=32).evaluate()
        prompt = np.arange(1, 7, dtype=np.int32)
        with ServingEngine(lm, max_len=32, slots=2, buckets=(8,),
                           name="lm") as eng:
            res = eng.submit(prompt, 4).result(timeout=180)
        assert res.n_generated == 4
        log = obs_access_log.from_env()
        log.close()
        with open(log.path) as f:
            recs = [json.loads(line) for line in f if line.strip()]
        ok = [r for r in recs if r["outcome"] == "ok"]
        assert len(ok) == 1
        r = ok[0]
        assert r["tenant"] == "lm"
        assert r["phase"] == "decode"
        assert r["prompt_tokens"] == 6
        assert r["output_tokens"] == 4
        assert r["ttft_ms"] is not None and r["ttft_ms"] >= 0
        assert r["e2e_ms"] is not None and r["e2e_ms"] > 0
        assert r["trace_id"] == res.trace_id


# ------------------------------------------------------------ cli rendering
class TestTopRendering:
    def test_renders_hbm_and_host_columns(self):
        text = "\n".join([
            "bigdl_train_throughput 100.0",
            "bigdl_device_hbm_bytes_in_use 2147483648",
            "bigdl_device_hbm_peak_bytes 3221225472",
            "bigdl_device_hbm_headroom 0.25",
            "bigdl_device_live_buffers 12",
            "bigdl_device_live_buffer_bytes 1048576",
            'bigdl_obs_host_up{host="0"} 1',
            'bigdl_obs_host_age_seconds{host="0"} 0.5',
            'bigdl_train_throughput{host="0"} 100.0',
            'bigdl_device_hbm_bytes_in_use{host="0"} 2147483648',
            'bigdl_obs_host_up{host="1"} 0',
            'bigdl_obs_host_age_seconds{host="1"} 42',
            'bigdl_train_throughput{host="1"} 99.0',
        ])
        frame = cli._render_top(exporter.parse_metrics(text))
        assert "hbm 2.0GB" in frame
        assert "peak 3.0GB" in frame
        assert "headroom 25.0%" in frame
        assert "hosts" in frame
        host_lines = {ln.split()[0]: ln for ln in frame.splitlines()
                      if ln.startswith("    ")}
        assert "up" in host_lines["0"]
        # dead host: stale-stamped, absent hbm renders "-" (never garbage)
        assert "STALE" in host_lines["1"]
        assert "hbm -" in host_lines["1"]

    def test_all_absent_renders_dashes_not_crashes(self):
        frame = cli._render_top({})
        assert "hbm -" in frame
        assert "headroom -" in frame
        assert "hosts" not in frame
