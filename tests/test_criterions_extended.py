"""Round-3 criterion/layer coverage sweep with torch oracles where torch has the
op (SURVEY.md §4: oracle-comparison is the reference's test backbone)."""

import jax.numpy as jnp
import numpy as np
import pytest
import torch
import torch.nn.functional as F

from bigdl_tpu import nn
from bigdl_tpu.optim import TreeNNAccuracy
from bigdl_tpu.utils.random_generator import RandomGenerator
from bigdl_tpu.utils.table import T


def _np(*shape, seed=0):
    return np.random.default_rng(seed).normal(size=shape).astype(np.float32)


class TestTorchOracleCriterions:
    def test_margin_ranking(self):
        x1, x2 = _np(8), _np(8, seed=1)
        y = np.sign(_np(8, seed=2)).astype(np.float32)
        ours = float(nn.MarginRankingCriterion(margin=0.3).forward(
            T(jnp.asarray(x1), jnp.asarray(x2)), jnp.asarray(y)))
        ref = F.margin_ranking_loss(torch.tensor(x1), torch.tensor(x2),
                                    torch.tensor(y), margin=0.3).item()
        assert ours == pytest.approx(ref, rel=1e-5)

    @pytest.mark.parametrize("p", [1, 2])
    def test_multi_margin(self, p):
        x = _np(6, 5)
        y = np.random.default_rng(3).integers(0, 5, size=6)
        ours = float(nn.MultiMarginCriterion(p=p, margin=1.0).forward(
            jnp.asarray(x), jnp.asarray(y)))
        ref = F.multi_margin_loss(torch.tensor(x), torch.tensor(y), p=p,
                                  margin=1.0).item()
        assert ours == pytest.approx(ref, rel=1e-5)

    def test_multi_margin_weighted(self):
        x = _np(6, 5)
        y = np.random.default_rng(3).integers(0, 5, size=6)
        w = np.abs(_np(5, seed=4)) + 0.1
        ours = float(nn.MultiMarginCriterion(weights=w).forward(
            jnp.asarray(x), jnp.asarray(y)))
        ref = F.multi_margin_loss(torch.tensor(x), torch.tensor(y),
                                  weight=torch.tensor(w)).item()
        assert ours == pytest.approx(ref, rel=1e-5)

    def test_multilabel_margin(self):
        x = _np(4, 6)
        # torch convention: 0-based labels, -1 padding, labels stop at first -1
        y = np.array([[1, 3, -1, -1, -1, -1],
                      [0, -1, -1, -1, -1, -1],
                      [2, 4, 5, -1, -1, -1],
                      [5, -1, -1, -1, -1, -1]], np.int64)
        ours = float(nn.MultiLabelMarginCriterion().forward(
            jnp.asarray(x), jnp.asarray(y)))
        ref = F.multilabel_margin_loss(torch.tensor(x), torch.tensor(y)).item()
        assert ours == pytest.approx(ref, rel=1e-5)

    def test_soft_margin(self):
        x = _np(3, 4)
        y = np.sign(_np(3, 4, seed=1)).astype(np.float32)
        ours = float(nn.SoftMarginCriterion().forward(jnp.asarray(x), jnp.asarray(y)))
        ref = F.soft_margin_loss(torch.tensor(x), torch.tensor(y)).item()
        assert ours == pytest.approx(ref, rel=1e-5)

    def test_cosine_distance_criterion(self):
        x, t = _np(4, 8), _np(4, 8, seed=1)
        ours = float(nn.CosineDistanceCriterion().forward(jnp.asarray(x),
                                                          jnp.asarray(t)))
        ref = (1.0 - F.cosine_similarity(torch.tensor(x),
                                         torch.tensor(t))).mean().item()
        assert ours == pytest.approx(ref, rel=1e-5)

    def test_l1_hinge_embedding(self):
        x1, x2 = _np(5, 6), _np(5, 6, seed=1)
        y = np.sign(_np(5, seed=2)).astype(np.float32)
        ours = float(nn.L1HingeEmbeddingCriterion(margin=1.5).forward(
            T(jnp.asarray(x1), jnp.asarray(x2)), jnp.asarray(y)))
        d = torch.pairwise_distance(torch.tensor(x1), torch.tensor(x2), p=1,
                                    eps=0.0)
        ref = F.hinge_embedding_loss(d, torch.tensor(y), margin=1.5).item()
        assert ours == pytest.approx(ref, rel=1e-4)

    def test_poisson(self):
        rate = np.abs(_np(3, 4)) + 0.1
        t = np.abs(_np(3, 4, seed=1)) + 0.1
        ours = float(nn.PoissonCriterion().forward(jnp.asarray(rate),
                                                   jnp.asarray(t)))
        ref = F.poisson_nll_loss(torch.tensor(rate), torch.tensor(t),
                                 log_input=False, full=False).item()
        assert ours == pytest.approx(ref, rel=1e-5)


class TestHandOracleCriterions:
    def test_cosine_proximity(self):
        x, t = _np(4, 6), _np(4, 6, seed=1)
        ours = float(nn.CosineProximityCriterion().forward(jnp.asarray(x),
                                                           jnp.asarray(t)))
        ref = -F.cosine_similarity(torch.tensor(x), torch.tensor(t)).mean().item()
        assert ours == pytest.approx(ref, rel=1e-5)

    def test_mape(self):
        x = _np(3, 4)
        t = _np(3, 4, seed=1) + 2.0
        ours = float(nn.MeanAbsolutePercentageCriterion().forward(
            jnp.asarray(x), jnp.asarray(t)))
        ref = 100.0 * np.mean(np.abs(t - x) / np.maximum(np.abs(t), 1e-7))
        assert ours == pytest.approx(float(ref), rel=1e-5)

    def test_msle(self):
        x = np.abs(_np(3, 4))
        t = np.abs(_np(3, 4, seed=1))
        ours = float(nn.MeanSquaredLogarithmicCriterion().forward(
            jnp.asarray(x), jnp.asarray(t)))
        ref = np.mean((np.log1p(t) - np.log1p(x)) ** 2)
        assert ours == pytest.approx(float(ref), rel=1e-5)

    def test_kld_probabilities(self):
        rng = np.random.default_rng(0)
        x = rng.dirichlet(np.ones(5), size=3).astype(np.float32)
        t = rng.dirichlet(np.ones(5), size=3).astype(np.float32)
        ours = float(nn.KullbackLeiblerDivergenceCriterion().forward(
            jnp.asarray(x), jnp.asarray(t)))
        ref = np.mean(np.sum(t * np.log(np.clip(t, 1e-7, 1) /
                                        np.clip(x, 1e-7, 1)), axis=-1))
        assert ours == pytest.approx(float(ref), rel=1e-4)

    def test_class_simplex_properties(self):
        c = nn.ClassSimplexCriterion(4)
        v = np.asarray(c._simplex)
        # vertices are unit-norm and pairwise equidistant
        np.testing.assert_allclose(np.linalg.norm(v, axis=1), 1.0, atol=1e-5)
        dists = [np.linalg.norm(v[i] - v[j])
                 for i in range(4) for j in range(i + 1, 4)]
        np.testing.assert_allclose(dists, dists[0], rtol=1e-4)
        # zero loss at the exact vertex
        y = np.array([2, 0], np.int64)
        loss = float(c.forward(jnp.asarray(v[y]), jnp.asarray(y)))
        assert loss == pytest.approx(0.0, abs=1e-10)

    def test_gradients_flow(self):
        """Every new criterion is differentiable wrt its input."""
        import jax
        cases = [
            (nn.SoftMarginCriterion(), _np(3, 4),
             np.sign(_np(3, 4, seed=1)).astype(np.float32)),
            (nn.MultiMarginCriterion(), _np(3, 4),
             np.array([0, 2, 3], np.int64)),
            (nn.CosineDistanceCriterion(), _np(3, 4), _np(3, 4, seed=1)),
            (nn.PoissonCriterion(), np.abs(_np(3, 4)) + 0.1,
             np.abs(_np(3, 4, seed=1))),
            (nn.MeanSquaredLogarithmicCriterion(), np.abs(_np(3, 4)),
             np.abs(_np(3, 4, seed=1))),
        ]
        for crit, x, t in cases:
            g = jax.grad(lambda a: crit.apply(a, jnp.asarray(t)))(jnp.asarray(x))
            assert np.isfinite(np.asarray(g)).all(), type(crit).__name__
            assert np.abs(np.asarray(g)).max() > 0, type(crit).__name__


class TestNewLayers:
    def test_bottle_equals_manual_reshape(self):
        RandomGenerator.set_seed(0)
        lin = nn.Linear(4, 2)
        b = nn.Bottle(lin)
        x = jnp.asarray(_np(3, 5, 4))
        out = b.evaluate().forward(x)
        assert out.shape == (3, 5, 2)
        direct = lin.evaluate().forward(x.reshape(15, 4)).reshape(3, 5, 2)
        np.testing.assert_allclose(np.asarray(out), np.asarray(direct), rtol=1e-6)

    def test_bottle_gradients(self):
        RandomGenerator.set_seed(0)
        b = nn.Bottle(nn.Linear(4, 2))
        x = jnp.asarray(_np(3, 5, 4))
        y = b.training().forward(x)
        gi = b.backward(x, jnp.ones_like(y))
        assert gi.shape == x.shape
        assert np.abs(np.asarray(gi)).max() > 0

    def test_cosine_layer_oracle(self):
        RandomGenerator.set_seed(0)
        m = nn.Cosine(6, 3)
        x = _np(4, 6)
        out = np.asarray(m.evaluate().forward(jnp.asarray(x)))
        w = np.asarray(m.get_params()["weight"])
        for o in range(3):
            ref = F.cosine_similarity(torch.tensor(x),
                                      torch.tensor(w[o]).expand(4, -1)).numpy()
            np.testing.assert_allclose(out[:, o], ref, rtol=1e-5, atol=1e-6)

    def test_cosine_distance_layer(self):
        x1, x2 = _np(4, 6), _np(4, 6, seed=1)
        m = nn.CosineDistance()
        out = np.asarray(m.evaluate().forward(T(jnp.asarray(x1), jnp.asarray(x2))))
        ref = F.cosine_similarity(torch.tensor(x1), torch.tensor(x2)).numpy()
        np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-6)

    def test_hash_bucket_embedding(self):
        RandomGenerator.set_seed(0)
        m = nn.HashBucketEmbedding(16, 4)
        big_ids = jnp.asarray([[0, 123456789], [99999, 7]], jnp.int32)
        out = m.evaluate().forward(big_ids)
        assert out.shape == (2, 2, 4)
        # deterministic: same ids → same rows
        out2 = m.forward(big_ids)
        np.testing.assert_array_equal(np.asarray(out), np.asarray(out2))
        # embeddings are trainable (gradient reaches the table)
        import jax
        g = jax.grad(lambda p: jnp.sum(
            m.apply(p, {}, big_ids, training=True)[0]))(m.get_params())
        assert np.abs(np.asarray(g["weight"])).sum() > 0


class TestTreeNNAccuracy:
    def test_root_node_accuracy(self):
        # (N=3, nodes=2, classes=3); root predictions: 2, 0, 1
        out = np.zeros((3, 2, 3), np.float32)
        out[0, 0, 2] = 1.0
        out[1, 0, 0] = 1.0
        out[2, 0, 1] = 1.0
        out[:, 1, :] = 99.0  # non-root nodes must be ignored
        target = np.array([2, 0, 0], np.int64)
        r = TreeNNAccuracy().apply(out, target)
        v, n = r.result()
        assert n == 3 and v == pytest.approx(2 / 3)

    def test_per_node_targets_and_2d_output(self):
        out = np.eye(4, dtype=np.float32)  # (4, 4) plain logits
        target = np.arange(4)
        v, n = TreeNNAccuracy().apply(out, target).result()
        assert v == 1.0 and n == 4
