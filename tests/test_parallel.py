"""Ring attention + tensor parallelism tests on the virtual 8-device CPU mesh.

Oracle (SURVEY.md §4): single-device full attention is the independent
implementation ring attention must match exactly.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from bigdl_tpu import nn
from bigdl_tpu.parallel import (
    TPRules, column_parallel, full_attention, megatron_mlp_rules, ring_attention,
    row_parallel,
)
from bigdl_tpu.utils.engine import Engine


def _qkv(b=2, h=2, t=16, d=8, seed=0):
    rng = np.random.default_rng(seed)
    mk = lambda: jnp.asarray(rng.normal(size=(b, h, t, d)).astype(np.float32))
    return mk(), mk(), mk()


class TestRingAttention:
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_full_attention(self, causal):
        Engine.init(mesh_shape=(1, 8), mesh_axes=(Engine.DATA_AXIS, Engine.SEQ_AXIS))
        q, k, v = _qkv()
        out = ring_attention(q, k, v, causal=causal)
        ref = full_attention(q, k, v, causal=causal)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=1e-5, rtol=1e-5)

    def test_under_jit(self):
        Engine.init(mesh_shape=(1, 8), mesh_axes=(Engine.DATA_AXIS, Engine.SEQ_AXIS))
        q, k, v = _qkv(t=24)
        fn = jax.jit(lambda q, k, v: ring_attention(q, k, v, causal=True))
        np.testing.assert_allclose(np.asarray(fn(q, k, v)),
                                   np.asarray(full_attention(q, k, v, causal=True)),
                                   atol=1e-5, rtol=1e-5)

    def test_gradients_match(self):
        Engine.init(mesh_shape=(1, 8), mesh_axes=(Engine.DATA_AXIS, Engine.SEQ_AXIS))
        q, k, v = _qkv(t=8)

        g_ring = jax.grad(lambda q: ring_attention(q, k, v, causal=True).sum())(q)
        g_full = jax.grad(lambda q: full_attention(q, k, v, causal=True).sum())(q)
        np.testing.assert_allclose(np.asarray(g_ring), np.asarray(g_full),
                                   atol=1e-4, rtol=1e-4)

    def test_no_seq_axis_falls_back(self):
        Engine.init(mesh_shape=(8,), mesh_axes=(Engine.DATA_AXIS,))
        q, k, v = _qkv()
        out = ring_attention(q, k, v)  # no 'seq' axis → full attention
        np.testing.assert_allclose(np.asarray(out),
                                   np.asarray(full_attention(q, k, v)), atol=1e-6)

    def test_indivisible_seq_raises(self):
        Engine.init(mesh_shape=(1, 8), mesh_axes=(Engine.DATA_AXIS, Engine.SEQ_AXIS))
        q, k, v = _qkv(t=12)  # 12 % 8 != 0
        with pytest.raises(ValueError, match="divisible"):
            ring_attention(q, k, v)


class TestMultiHeadAttention:
    def test_ring_equals_full_impl(self):
        Engine.init(mesh_shape=(1, 8), mesh_axes=(Engine.DATA_AXIS, Engine.SEQ_AXIS))
        mha = nn.MultiHeadAttention(16, 4, causal=True, attention_impl="ring")
        x = jnp.asarray(np.random.default_rng(0).normal(
            size=(2, 16, 16)).astype(np.float32))
        out_ring = mha.evaluate().forward(x)
        mha_full = nn.MultiHeadAttention(16, 4, causal=True, attention_impl="full")
        mha_full.set_params(mha.get_params())
        out_full = mha_full.evaluate().forward(x)
        np.testing.assert_allclose(np.asarray(out_ring), np.asarray(out_full),
                                   atol=1e-5, rtol=1e-5)

    def test_trains_in_local_optimizer(self):
        from bigdl_tpu.dataset import DataSet
        from bigdl_tpu.dataset.sample import Sample, SampleToMiniBatch
        from bigdl_tpu.optim import Adam, LocalOptimizer, Trigger

        Engine.init(mesh_shape=(1, 8), mesh_axes=(Engine.DATA_AXIS, Engine.SEQ_AXIS))
        rng = np.random.default_rng(0)
        samples = [Sample(rng.normal(size=(8, 12)).astype(np.float32),
                          np.int32(rng.integers(0, 4))) for _ in range(32)]
        data = DataSet.array(samples) >> SampleToMiniBatch(8)
        model = (nn.Sequential()
                 .add(nn.MultiHeadAttention(12, 3, causal=True))
                 .add(nn.Select(2, -1))
                 .add(nn.Linear(12, 4)).add(nn.LogSoftMax()))
        opt = (LocalOptimizer(model, data, nn.ClassNLLCriterion())
               .set_optim_method(Adam(learningrate=0.01))
               .set_end_when(Trigger.max_iteration(8)))
        opt.optimize()
        assert np.isfinite(opt.state["loss"])


class TestTensorParallel:
    def test_rules_match_and_validate(self):
        Engine.init(mesh_shape=(2, 4), mesh_axes=(Engine.DATA_AXIS, Engine.MODEL_AXIS))
        mesh = Engine.mesh()
        params = {"0": {"weight": np.zeros((8, 4)), "bias": np.zeros((8,))},
                  "1": {"weight": np.zeros((4, 8)), "bias": np.zeros((4,))}}
        rules = TPRules([("0/weight", column_parallel()),
                         ("0/bias", P("model")),
                         ("1/weight", row_parallel())])
        sh = rules.param_shardings(params, mesh)
        assert sh["0"]["weight"].spec == P("model", None)
        assert sh["1"]["weight"].spec == P(None, "model")
        assert sh["1"]["bias"].spec == P()  # default replicated

    def test_indivisible_dim_rejected(self):
        Engine.init(mesh_shape=(2, 4), mesh_axes=(Engine.DATA_AXIS, Engine.MODEL_AXIS))
        rules = TPRules([("weight", column_parallel())])
        with pytest.raises(ValueError, match="divisible"):
            rules.param_shardings({"weight": np.zeros((6, 4))}, Engine.mesh())

    def test_unknown_axis_rejected(self):
        Engine.init(mesh_shape=(8,), mesh_axes=(Engine.DATA_AXIS,))
        rules = TPRules([("weight", column_parallel())])
        with pytest.raises(ValueError, match="mesh axis"):
            rules.param_shardings({"weight": np.zeros((8, 4))}, Engine.mesh())

    def test_tp_training_matches_replicated(self):
        """TP=4 training must produce the same params as replicated training."""
        from bigdl_tpu.dataset import DataSet
        from bigdl_tpu.dataset.sample import Sample, SampleToMiniBatch
        from bigdl_tpu.optim import DistriOptimizer, SGD, Trigger
        from bigdl_tpu.utils.random_generator import RandomGenerator

        rng = np.random.default_rng(0)
        samples = [Sample(rng.normal(size=(16,)).astype(np.float32),
                          np.int32(rng.integers(0, 4))) for _ in range(64)]

        def build():
            RandomGenerator.set_seed(42)
            return (nn.Sequential()
                    .add(nn.Linear(16, 32)).add(nn.ReLU())
                    .add(nn.Linear(32, 4)).add(nn.LogSoftMax()))

        results = {}
        for mode in ("replicated", "tp"):
            Engine.reset()
            Engine.init(mesh_shape=(2, 4),
                        mesh_axes=(Engine.DATA_AXIS, Engine.MODEL_AXIS))
            data = DataSet.array(samples, distributed=True) >> SampleToMiniBatch(16)
            model = build()
            opt = (DistriOptimizer(model, data, nn.ClassNLLCriterion())
                   .set_optim_method(SGD(learningrate=0.1))
                   .set_end_when(Trigger.max_iteration(5)))
            if mode == "tp":
                opt.set_tensor_parallel(megatron_mlp_rules("0", "2"))
            opt.optimize()
            results[mode] = jax.tree_util.tree_map(np.asarray, model.get_params())

        flat_r = jax.tree_util.tree_leaves(results["replicated"])
        flat_t = jax.tree_util.tree_leaves(results["tp"])
        for a, b in zip(flat_r, flat_t):
            np.testing.assert_allclose(a, b, atol=1e-5, rtol=1e-5)

    def test_tp_with_zero1(self):
        from bigdl_tpu.dataset import DataSet
        from bigdl_tpu.dataset.sample import Sample, SampleToMiniBatch
        from bigdl_tpu.optim import DistriOptimizer, SGD, Trigger

        Engine.init(mesh_shape=(2, 4), mesh_axes=(Engine.DATA_AXIS, Engine.MODEL_AXIS))
        rng = np.random.default_rng(0)
        samples = [Sample(rng.normal(size=(16,)).astype(np.float32),
                          np.int32(rng.integers(0, 4))) for _ in range(32)]
        data = DataSet.array(samples, distributed=True) >> SampleToMiniBatch(16)
        model = (nn.Sequential()
                 .add(nn.Linear(16, 32)).add(nn.ReLU())
                 .add(nn.Linear(32, 4)).add(nn.LogSoftMax()))
        opt = (DistriOptimizer(model, data, nn.ClassNLLCriterion(),
                               parameter_sync="zero1")
               .set_optim_method(SGD(learningrate=0.1, momentum=0.9))
               .set_end_when(Trigger.max_iteration(4))
               .set_tensor_parallel(megatron_mlp_rules("0", "2")))
        opt.optimize()
        assert np.isfinite(opt.state["loss"])


class TestReviewRegressions:
    def test_anchored_rules_no_index_collision(self):
        Engine.init(mesh_shape=(2, 4), mesh_axes=(Engine.DATA_AXIS, Engine.MODEL_AXIS))
        rules = megatron_mlp_rules("1", "3")
        params = {"1": {"weight": np.zeros((8, 4))},
                  "11": {"weight": np.zeros((7, 3))}}  # indivisible: must NOT match
        sh = rules.param_shardings(params, Engine.mesh())
        assert sh["1"]["weight"].spec == P("model", None)
        assert sh["11"]["weight"].spec == P()  # no collision with "1"

    def test_slot_shardings_mirror_params(self):
        Engine.init(mesh_shape=(2, 4), mesh_axes=(Engine.DATA_AXIS, Engine.MODEL_AXIS))
        rules = megatron_mlp_rules("0", "2")
        slots = {"v": {"0": {"weight": np.zeros((8, 4))},
                       "2": {"weight": np.zeros((4, 8))},
                       "1": {"bias": np.zeros((16,))}}}
        sh = rules.slot_shardings(slots, Engine.mesh(), dp_axis=None)
        assert sh["v"]["0"]["weight"].spec == P("model", None)
        assert sh["v"]["2"]["weight"].spec == P(None, "model")
        assert sh["v"]["1"]["bias"].spec == P()  # allreduce mode: replicated
        sh_z = rules.slot_shardings(slots, Engine.mesh(), dp_axis=Engine.DATA_AXIS)
        assert sh_z["v"]["1"]["bias"].spec == P("data")  # zero1: data-sharded

    def test_ring_attention_dp_sp_mesh(self):
        Engine.init(mesh_shape=(2, 4), mesh_axes=(Engine.DATA_AXIS, Engine.SEQ_AXIS))
        q, k, v = _qkv(b=4, t=8)
        out = ring_attention(q, k, v, causal=True)
        ref = full_attention(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=1e-5, rtol=1e-5)
