"""Round-3 misc layer sweep with torch oracles where torch has the op."""

import jax.numpy as jnp
import numpy as np
import pytest
import torch
import torch.nn.functional as F

from bigdl_tpu import nn
from bigdl_tpu.utils.random_generator import RandomGenerator
from bigdl_tpu.utils.table import T


def _np(*shape, seed=0):
    return np.random.default_rng(seed).normal(size=shape).astype(np.float32)


class TestActivations:
    def test_threshold_oracle(self):
        x = _np(3, 4)
        out = np.asarray(nn.Threshold(0.2, -5.0).evaluate().forward(jnp.asarray(x)))
        ref = F.threshold(torch.tensor(x), 0.2, -5.0).numpy()
        np.testing.assert_allclose(out, ref, rtol=1e-6)

    def test_hardshrink_oracle(self):
        x = _np(3, 4)
        out = np.asarray(nn.HardShrink(0.4).evaluate().forward(jnp.asarray(x)))
        np.testing.assert_allclose(out, F.hardshrink(torch.tensor(x), 0.4).numpy(),
                                   rtol=1e-6)

    def test_softshrink_oracle(self):
        x = _np(3, 4)
        out = np.asarray(nn.SoftShrink(0.4).evaluate().forward(jnp.asarray(x)))
        np.testing.assert_allclose(out, F.softshrink(torch.tensor(x), 0.4).numpy(),
                                   rtol=1e-6)

    def test_rrelu_eval_oracle(self):
        x = _np(3, 4)
        m = nn.RReLU(0.1, 0.3).evaluate()
        out = np.asarray(m.forward(jnp.asarray(x)))
        ref = F.rrelu(torch.tensor(x), 0.1, 0.3, training=False).numpy()
        np.testing.assert_allclose(out, ref, rtol=1e-6)

    def test_rrelu_training_in_range(self):
        RandomGenerator.set_seed(0)
        x = -np.abs(_np(50, 50)) - 0.1  # all negative
        m = nn.RReLU(0.1, 0.3).training()
        out = np.asarray(m.forward(jnp.asarray(x)))
        slope = out / x
        assert slope.min() >= 0.1 - 1e-6 and slope.max() <= 0.3 + 1e-6
        assert slope.std() > 0.01  # actually random, not a constant

    def test_negative(self):
        x = _np(2, 3)
        np.testing.assert_allclose(
            np.asarray(nn.Negative().evaluate().forward(jnp.asarray(x))), -x)


class TestReductionsAndTableOps:
    def test_reductions(self):
        x = _np(3, 4)
        np.testing.assert_allclose(
            np.asarray(nn.Max(2).evaluate().forward(jnp.asarray(x))), x.max(1),
            rtol=1e-6)
        np.testing.assert_allclose(
            np.asarray(nn.Min(1).evaluate().forward(jnp.asarray(x))), x.min(0),
            rtol=1e-6)
        np.testing.assert_allclose(
            np.asarray(nn.Mean(2).evaluate().forward(jnp.asarray(x))), x.mean(1),
            rtol=1e-6)
        np.testing.assert_allclose(
            np.asarray(nn.Sum(2).evaluate().forward(jnp.asarray(x))), x.sum(1),
            rtol=1e-5)
        np.testing.assert_allclose(
            np.asarray(nn.Sum(2, size_average=True).evaluate()
                       .forward(jnp.asarray(x))), x.mean(1), rtol=1e-6)

    def test_negative_dim_with_batch_hint(self):
        """dim=-1 with n_input_dims set must not double-shift (review fix)."""
        x = _np(8, 3, 4)
        out = np.asarray(nn.Sum(-1, n_input_dims=2).evaluate()
                         .forward(jnp.asarray(x)))
        np.testing.assert_allclose(out, x.sum(-1), rtol=1e-5)
        out2 = np.asarray(nn.Max(1, n_input_dims=2).evaluate()
                          .forward(jnp.asarray(x)))
        np.testing.assert_allclose(out2, x.max(1), rtol=1e-6)

    def test_table_algebra(self):
        a, b = _np(2, 3), np.abs(_np(2, 3, seed=1)) + 0.5
        ja, jb = jnp.asarray(a), jnp.asarray(b)
        np.testing.assert_allclose(
            np.asarray(nn.CSubTable().evaluate().forward(T(ja, jb))), a - b,
            rtol=1e-6)
        np.testing.assert_allclose(
            np.asarray(nn.CDivTable().evaluate().forward(T(ja, jb))), a / b,
            rtol=1e-5)
        np.testing.assert_allclose(
            np.asarray(nn.CMaxTable().evaluate().forward(T(ja, jb))),
            np.maximum(a, b), rtol=1e-6)
        np.testing.assert_allclose(
            np.asarray(nn.CMinTable().evaluate().forward(T(ja, jb))),
            np.minimum(a, b), rtol=1e-6)

    def test_mm_mv_dot(self):
        a, b = _np(2, 3, 4), _np(2, 4, 5, seed=1)
        np.testing.assert_allclose(
            np.asarray(nn.MM().evaluate().forward(T(jnp.asarray(a), jnp.asarray(b)))),
            a @ b, rtol=1e-5)
        np.testing.assert_allclose(
            np.asarray(nn.MM(trans_a=True).evaluate().forward(
                T(jnp.asarray(_np(2, 4, 3)), jnp.asarray(b)))),
            _np(2, 4, 3).transpose(0, 2, 1) @ b, rtol=1e-5)
        v = _np(2, 4, seed=2)
        np.testing.assert_allclose(
            np.asarray(nn.MV().evaluate().forward(T(jnp.asarray(a), jnp.asarray(v)))),
            np.einsum("bij,bj->bi", a, v), rtol=1e-5)
        x, y = _np(3, 5), _np(3, 5, seed=1)
        np.testing.assert_allclose(
            np.asarray(nn.DotProduct().evaluate().forward(
                T(jnp.asarray(x), jnp.asarray(y)))),
            (x * y).sum(1), rtol=1e-5)


class TestParamLayers:
    def test_bilinear_torch_oracle(self):
        RandomGenerator.set_seed(0)
        m = nn.Bilinear(3, 4, 2).evaluate()
        x1, x2 = _np(5, 3), _np(5, 4, seed=1)
        out = np.asarray(m.forward(T(jnp.asarray(x1), jnp.asarray(x2))))
        w = torch.tensor(np.asarray(m.get_params()["weight"]))
        b = torch.tensor(np.asarray(m.get_params()["bias"]))
        ref = F.bilinear(torch.tensor(x1), torch.tensor(x2), w, b).numpy()
        np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-6)

    def test_euclidean_oracle(self):
        RandomGenerator.set_seed(0)
        m = nn.Euclidean(4, 3).evaluate()
        x = _np(2, 4)
        out = np.asarray(m.forward(jnp.asarray(x)))
        w = np.asarray(m.get_params()["weight"])
        ref = np.sqrt(((x[:, None, :] - w[None]) ** 2).sum(-1) + 1e-12)
        np.testing.assert_allclose(out, ref, rtol=1e-5)

    def test_maxout_equals_reshape_max(self):
        RandomGenerator.set_seed(0)
        m = nn.Maxout(4, 3, 2).evaluate()
        x = _np(5, 4)
        out = np.asarray(m.forward(jnp.asarray(x)))
        w = np.asarray(m.get_params()["weight"])
        b = np.asarray(m.get_params()["bias"])
        ref = (x @ w.T + b).reshape(5, 3, 2).max(-1)
        np.testing.assert_allclose(out, ref, rtol=1e-5)
        assert out.shape == (5, 3)


class TestUpsampling:
    def test_nearest_torch_oracle(self):
        x = _np(1, 2, 3, 3)
        out = np.asarray(nn.SpatialUpSamplingNearest(2).evaluate()
                         .forward(jnp.asarray(x)))
        ref = F.interpolate(torch.tensor(x), scale_factor=2,
                            mode="nearest").numpy()
        np.testing.assert_allclose(out, ref, rtol=1e-6)

    def test_bilinear_torch_oracle(self):
        x = _np(1, 2, 4, 4)
        out = np.asarray(nn.SpatialUpSamplingBilinear(2).evaluate()
                         .forward(jnp.asarray(x)))
        ref = F.interpolate(torch.tensor(x), scale_factor=2, mode="bilinear",
                            align_corners=True).numpy()
        np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-6)

    def test_gradients_flow(self):
        import jax
        RandomGenerator.set_seed(0)
        for m, x in [(nn.Bilinear(3, 4, 2), T(jnp.asarray(_np(2, 3)),
                                              jnp.asarray(_np(2, 4, seed=1)))),
                     (nn.Maxout(4, 3, 2), jnp.asarray(_np(2, 4))),
                     (nn.Euclidean(4, 3), jnp.asarray(_np(2, 4)))]:
            def loss(p):
                out, _ = m.apply(p, {}, x, training=True)
                return jnp.sum(out)
            g = jax.grad(loss)(m.get_params())
            leaves = jax.tree_util.tree_leaves(g)
            assert all(np.isfinite(np.asarray(l)).all() for l in leaves)
            assert any(np.abs(np.asarray(l)).max() > 0 for l in leaves)


class TestImageNormalize:
    """nn.ImageNormalize: the TPU-native uint8-feed input path (round 4)."""

    def test_uint8_matches_torchvision_semantics(self):
        import jax
        rng = np.random.default_rng(0)
        x = rng.integers(0, 256, size=(2, 3, 8, 8)).astype(np.uint8)
        m = nn.ImageNormalize()
        out, _ = m.apply({}, {}, jnp.asarray(x))
        mean = np.array([0.485, 0.456, 0.406]).reshape(1, 3, 1, 1)
        std = np.array([0.229, 0.224, 0.225]).reshape(1, 3, 1, 1)
        want = (x.astype(np.float32) / 255.0 - mean) / std
        assert np.allclose(np.asarray(out), want, atol=1e-5)

    def test_nhwc_layout(self):
        from bigdl_tpu.nn import layout
        rng = np.random.default_rng(1)
        x = rng.integers(0, 256, size=(2, 3, 8, 8)).astype(np.uint8)
        m = nn.ImageNormalize()
        o1, _ = m.apply({}, {}, jnp.asarray(x))
        layout.set_image_format("NHWC")
        try:
            o2, _ = m.apply({}, {}, jnp.asarray(x.transpose(0, 2, 3, 1)))
        finally:
            layout.set_image_format(None)
        assert np.allclose(np.transpose(np.asarray(o1), (0, 2, 3, 1)),
                           np.asarray(o2), atol=1e-5)

    def test_float_passthrough_keeps_dtype_and_scale(self):
        x = jnp.asarray(np.random.default_rng(2).normal(size=(2, 1, 4, 4)),
                        jnp.float32)
        m = nn.ImageNormalize(mean=(0.5,), std=(2.0,), scale=1.0)
        out, _ = m.apply({}, {}, x)
        assert out.dtype == jnp.float32
        assert np.allclose(np.asarray(out), (np.asarray(x) - 0.5) / 2.0,
                           atol=1e-6)

    def test_serializer_roundtrip(self, tmp_path):
        from bigdl_tpu.utils.serializer import load_module, save_module
        m = nn.Sequential().add(nn.ImageNormalize()).add(nn.Linear(3, 2))
        p = str(tmp_path / "m.bigdl")
        save_module(m, p)
        m2 = load_module(p)
        x = jnp.asarray(np.random.default_rng(3).integers(0, 256, (2, 3)),
                        jnp.uint8)
        o1, _ = m.apply(m.get_params(), m.get_state(), x)
        o2, _ = m2.apply(m2.get_params(), m2.get_state(), x)
        assert np.allclose(np.asarray(o1), np.asarray(o2), atol=1e-6)
