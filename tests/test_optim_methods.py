"""OptimMethod + LR schedule tests.

Oracle strategy (SURVEY.md §4 takeaway 1): torch.optim is the independent
implementation for Adagrad/Adadelta/Adamax/RMSprop; LBFGS is checked by
convergence on a strongly-convex quadratic; schedules against closed forms.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
import torch

from bigdl_tpu.optim import (
    Adadelta, Adagrad, Adam, Adamax, Ftrl, LBFGS, LarsSGD, RMSprop, SGD,
)
from bigdl_tpu.optim.schedules import (
    Default, Exponential, MultiStep, NaturalExp, Plateau, Poly, SequentialSchedule,
    Step, Warmup,
)


def _run_ours(method, w0, grads):
    params = {"w": jnp.asarray(w0)}
    state = method.init_state(params)
    for i, g in enumerate(grads):
        params, state = method.update(params, {"w": jnp.asarray(g)}, state,
                                      jnp.asarray(i))
    return np.asarray(params["w"])


def _run_torch(opt_ctor, w0, grads):
    w = torch.tensor(w0, requires_grad=True)
    opt = opt_ctor([w])
    for g in grads:
        opt.zero_grad()
        w.grad = torch.tensor(g)
        opt.step()
    return w.detach().numpy()


@pytest.fixture
def problem():
    rng = np.random.default_rng(42)
    w0 = rng.normal(size=(5, 3)).astype(np.float32)
    grads = [rng.normal(size=(5, 3)).astype(np.float32) for _ in range(6)]
    return w0, grads


class TestVsTorchOracles:
    def test_adagrad(self, problem):
        w0, grads = problem
        ours = _run_ours(Adagrad(learningrate=0.1), w0, grads)
        ref = _run_torch(lambda p: torch.optim.Adagrad(p, lr=0.1), w0, grads)
        np.testing.assert_allclose(ours, ref, atol=1e-5)

    def test_adadelta(self, problem):
        w0, grads = problem
        ours = _run_ours(Adadelta(decayrate=0.9, epsilon=1e-6, learningrate=0.5),
                         w0, grads)
        ref = _run_torch(lambda p: torch.optim.Adadelta(p, lr=0.5, rho=0.9, eps=1e-6),
                         w0, grads)
        np.testing.assert_allclose(ours, ref, atol=1e-5)

    def test_adamax(self, problem):
        w0, grads = problem
        ours = _run_ours(Adamax(learningrate=0.02, epsilon=1e-8), w0, grads)
        ref = _run_torch(lambda p: torch.optim.Adamax(p, lr=0.02, eps=1e-8), w0, grads)
        np.testing.assert_allclose(ours, ref, atol=1e-5)

    def test_rmsprop(self, problem):
        w0, grads = problem
        ours = _run_ours(RMSprop(learningrate=0.01, decayrate=0.95, epsilon=1e-8),
                         w0, grads)
        ref = _run_torch(lambda p: torch.optim.RMSprop(p, lr=0.01, alpha=0.95,
                                                       eps=1e-8), w0, grads)
        np.testing.assert_allclose(ours, ref, atol=1e-5)


class TestConvergence:
    """Each method must minimize a strongly-convex quadratic under jit."""

    def _quadratic(self):
        rng = np.random.default_rng(0)
        Q = rng.normal(size=(12, 12)).astype(np.float32)
        A = Q @ Q.T + 10.0 * np.eye(12, dtype=np.float32)
        b = rng.normal(size=(12,)).astype(np.float32)
        return jnp.asarray(A), jnp.asarray(b)

    @pytest.mark.parametrize("method,iters", [
        (LBFGS(history=8, learningrate=1.0), 40),
        (Ftrl(learningrate=0.5), 300),
        (LarsSGD(learningrate=0.05, momentum=0.9, trust=1.0), 300),
        (Adam(learningrate=0.3), 300),
    ])
    def test_minimizes_quadratic(self, method, iters):
        A, b = self._quadratic()
        params = {"x": jnp.zeros(12)}
        state = method.init_state(params)

        @jax.jit
        def step(params, state, i):
            g = {"x": A @ params["x"] - b}
            return method.update(params, g, state, i)

        for i in range(iters):
            params, state = step(params, state, jnp.asarray(i))
        x_star = jnp.linalg.solve(A, b)
        f = lambda x: float(0.5 * x @ A @ x - b @ x)
        assert f(params["x"]) - f(x_star) < 1e-2

    def test_ftrl_l1_produces_sparsity(self):
        A, b = self._quadratic()
        method = Ftrl(learningrate=0.5, l1_regularization_strength=2.0)
        params = {"x": jnp.zeros(12)}
        state = method.init_state(params)
        for i in range(200):
            g = {"x": A @ params["x"] - b}
            params, state = method.update(params, g, state, jnp.asarray(i))
        assert int(np.sum(np.abs(np.asarray(params["x"])) < 1e-6)) > 0


class TestSchedules:
    def _lr(self, sched, base, step):
        return float(sched(jnp.asarray(base, jnp.float32),
                           jnp.asarray(step, jnp.float32)))

    def test_default(self):
        s = Default(learningrate_decay=0.1)
        assert self._lr(s, 1.0, 0) == pytest.approx(1.0)
        assert self._lr(s, 1.0, 10) == pytest.approx(0.5)

    def test_step(self):
        s = Step(step_size=10, gamma=0.5)
        assert self._lr(s, 1.0, 9) == pytest.approx(1.0)
        assert self._lr(s, 1.0, 10) == pytest.approx(0.5)
        assert self._lr(s, 1.0, 25) == pytest.approx(0.25)

    def test_multistep(self):
        s = MultiStep(step_sizes=[10, 30], gamma=0.1)
        assert self._lr(s, 1.0, 5) == pytest.approx(1.0)
        assert self._lr(s, 1.0, 15) == pytest.approx(0.1)
        assert self._lr(s, 1.0, 40) == pytest.approx(0.01)

    def test_poly(self):
        s = Poly(power=2.0, max_iteration=100)
        assert self._lr(s, 1.0, 0) == pytest.approx(1.0)
        assert self._lr(s, 1.0, 50) == pytest.approx(0.25)
        assert self._lr(s, 1.0, 100) == pytest.approx(0.0)
        assert self._lr(s, 1.0, 200) == pytest.approx(0.0)  # clamped past max

    def test_exponential(self):
        s = Exponential(decay_step=10, decay_rate=0.5)
        assert self._lr(s, 1.0, 10) == pytest.approx(0.5)
        s2 = Exponential(decay_step=10, decay_rate=0.5, stair_case=True)
        assert self._lr(s2, 1.0, 15) == pytest.approx(0.5)

    def test_natural_exp(self):
        s = NaturalExp(decay_step=1, decay_rate=0.1)
        assert self._lr(s, 1.0, 10) == pytest.approx(np.exp(-1.0), rel=1e-5)

    def test_warmup_sequential(self):
        # 5-iteration linear warmup 0.1→0.6, then Default decay from base 1.0
        seq = (SequentialSchedule()
               .add(Warmup(delta=0.1), 5)
               .add(Default(learningrate_decay=0.0), 1000))
        assert self._lr(seq, 0.1, 0) == pytest.approx(0.1)
        assert self._lr(seq, 0.1, 4) == pytest.approx(0.5)
        assert self._lr(seq, 0.1, 5) == pytest.approx(0.1)  # stage 2, its own base

    def test_plateau(self):
        p = Plateau(factor=0.5, patience=2, mode="min", epsilon=0.0)
        p.reset(1.0)
        assert p.on_metric(10.0) == 1.0   # first value = improvement
        assert p.on_metric(10.0) == 1.0   # wait 1
        assert p.on_metric(10.0) == 1.0   # wait 2
        assert p.on_metric(10.0) == 0.5   # patience exceeded → halve
        assert p.on_metric(5.0) == 0.5    # improvement resets wait

    def test_sgd_with_schedule_in_jit(self):
        method = SGD(learningrate=1.0, learningrate_schedule=Step(10, 0.1))
        params = {"w": jnp.ones(3)}
        state = method.init_state(params)

        @jax.jit
        def step(params, state, i):
            return method.update(params, {"w": jnp.ones(3)}, state, i)

        p0, state = step(params, state, jnp.asarray(0))
        np.testing.assert_allclose(np.asarray(p0["w"]), 0.0, atol=1e-6)  # lr=1
        p1, state = step(p0, state, jnp.asarray(10))
        np.testing.assert_allclose(np.asarray(p1["w"]), -0.1, atol=1e-6)  # lr=0.1

    def test_sgd_stateful_plateau_state_leaf(self):
        sched = Plateau(factor=0.1, patience=0, mode="min")
        method = SGD(learningrate=0.5, learningrate_schedule=sched)
        params = {"w": jnp.ones(2)}
        state = method.init_state(params)
        assert float(state["clr"]) == pytest.approx(0.5)
        # host lowers the LR leaf; update must honor it without re-tracing
        step_fn = jax.jit(lambda p, s, i: method.update(p, {"w": jnp.ones(2)}, s, i))
        p1, s1 = step_fn(params, state, jnp.asarray(0))
        np.testing.assert_allclose(np.asarray(p1["w"]), 0.5, atol=1e-6)
        s1["clr"] = jnp.asarray(0.05, jnp.float32)
        p2, _ = step_fn(p1, s1, jnp.asarray(1))
        np.testing.assert_allclose(np.asarray(p2["w"]), 0.45, atol=1e-6)

    def test_sgd_layer_lr_mults(self):
        method = SGD(learningrate=1.0, layer_lr_mults={"frozen": 0.0})
        params = {"frozen": jnp.ones(2), "hot": jnp.ones(2)}
        state = method.init_state(params)
        g = {"frozen": jnp.ones(2), "hot": jnp.ones(2)}
        new_p, _ = method.update(params, g, state, jnp.asarray(0))
        np.testing.assert_allclose(np.asarray(new_p["frozen"]), 1.0)
        np.testing.assert_allclose(np.asarray(new_p["hot"]), 0.0)
