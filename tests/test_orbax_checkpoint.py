"""Orbax checkpoint backend (SURVEY.md §5.4 — async per-leaf tensorstore
layout): save during training, resume, and retry-from-checkpoint."""

import os

import numpy as np
import pytest

from bigdl_tpu import Engine, nn
from bigdl_tpu.dataset import DataSet, SampleToMiniBatch
from bigdl_tpu.dataset.sample import Sample
from bigdl_tpu.optim import LocalOptimizer, SGD, Trigger


def _data(n=64, dim=6, classes=3, batch=16, seed=0):
    rng = np.random.default_rng(seed)
    return DataSet.array(
        [Sample(rng.normal(size=(dim,)).astype(np.float32),
                np.int32(rng.integers(0, classes))) for _ in range(n)]
    ) >> SampleToMiniBatch(batch)


def _model():
    return nn.Sequential().add(nn.Linear(6, 3)).add(nn.LogSoftMax())


class TestOrbaxBackend:
    def test_save_and_resume(self, tmp_path):
        Engine.init(seed=0)
        opt = (LocalOptimizer(_model(), _data(), nn.ClassNLLCriterion())
               .set_optim_method(SGD(learningrate=0.1, momentum=0.9,
                                     dampening=0.0))
               .set_checkpoint(str(tmp_path), Trigger.several_iteration(2),
                               backend="orbax")
               .set_end_when(Trigger.max_iteration(5)))
        opt.optimize()
        dirs = [p for p in os.listdir(tmp_path)
                if p.startswith("ckpt_orbax") and not p.endswith(".meta.json")]
        assert len(dirs) >= 2  # iters 2 and 4

        # resume into a FRESH optimizer
        opt2 = (LocalOptimizer(_model(), _data(), nn.ClassNLLCriterion())
                .set_optim_method(SGD(learningrate=0.1, momentum=0.9,
                                      dampening=0.0))
                .set_checkpoint(str(tmp_path), Trigger.several_iteration(2),
                                backend="orbax"))
        opt2._load_latest_checkpoint()
        assert opt2.state["neval"] == 4
        # resumed params equal the checkpointed ones, and training continues
        opt2.set_end_when(Trigger.max_iteration(8))
        opt2.optimize()
        assert opt2.state["neval"] >= 8
        assert np.isfinite(opt2.state["loss"])

    def test_retry_uses_orbax_checkpoint(self, tmp_path, monkeypatch):
        """The failure-retry loop recovers from an orbax checkpoint."""
        monkeypatch.setenv("BIGDL_FAILURE_RETRY_TIMES", "2")
        monkeypatch.setenv("BIGDL_FAILURE_RETRY_INTERVAL", "0")
        Engine.reset()
        Engine.init(seed=0)
        opt = (LocalOptimizer(_model(), _data(), nn.ClassNLLCriterion())
               .set_optim_method(SGD(learningrate=0.1))
               .set_checkpoint(str(tmp_path), Trigger.several_iteration(2),
                               backend="orbax")
               .set_end_when(Trigger.max_iteration(6)))

        calls = {"n": 0}
        orig = opt._optimize_impl

        def flaky():
            calls["n"] += 1
            if calls["n"] == 2:
                raise RuntimeError("injected failure")
            return orig()

        monkeypatch.setattr(opt, "_optimize_impl", flaky)
        opt.optimize()  # first run completes; call again to exercise retry
        opt.set_end_when(Trigger.max_iteration(12))
        opt.optimize()
        assert opt.state["neval"] >= 12

    def test_interrupted_save_skipped_on_resume(self, tmp_path):
        """A crash-interrupted save (array dir without the .meta.json commit
        marker) must not shadow an older committed checkpoint."""
        import time

        Engine.reset()
        Engine.init(seed=0)
        opt = (LocalOptimizer(_model(), _data(), nn.ClassNLLCriterion())
               .set_optim_method(SGD(learningrate=0.1))
               .set_checkpoint(str(tmp_path), Trigger.several_iteration(2),
                               backend="orbax")
               .set_end_when(Trigger.max_iteration(3)))
        opt.optimize()
        # fake an interrupted newer save: dir present, no commit marker
        time.sleep(0.05)
        os.makedirs(tmp_path / "ckpt_orbax.999")
        opt2 = (LocalOptimizer(_model(), _data(), nn.ClassNLLCriterion())
                .set_optim_method(SGD(learningrate=0.1))
                .set_checkpoint(str(tmp_path), Trigger.several_iteration(2),
                                backend="orbax"))
        opt2._load_latest_checkpoint()   # must pick the committed iter-2 ckpt
        assert opt2.state["neval"] == 2

    def test_invalid_backend_rejected(self, tmp_path):
        with pytest.raises(ValueError, match="backend"):
            LocalOptimizer(_model(), _data(), nn.ClassNLLCriterion()) \
                .set_checkpoint(str(tmp_path), Trigger.every_epoch(),
                                backend="zip")


class TestOverwriteMode:
    def test_rolling_keeps_exactly_latest_committed(self, tmp_path):
        Engine.reset()
        Engine.init(seed=0)
        opt = (LocalOptimizer(_model(), _data(), nn.ClassNLLCriterion())
               .set_optim_method(SGD(learningrate=0.1))
               .set_checkpoint(str(tmp_path), Trigger.several_iteration(2),
                               backend="orbax")
               .over_write_checkpoint()
               .set_end_when(Trigger.max_iteration(7)))
        opt.optimize()
        dirs = [p for p in os.listdir(tmp_path)
                if p.startswith("ckpt_orbax") and not p.endswith(".meta.json")]
        metas = [p for p in os.listdir(tmp_path) if p.endswith(".meta.json")]
        # pruning runs at the commit AFTER each save, so at most the latest
        # committed plus one in-flight survivor remain — never a full history
        assert len(dirs) <= 2 and len(metas) <= 2
        opt2 = (LocalOptimizer(_model(), _data(), nn.ClassNLLCriterion())
                .set_optim_method(SGD(learningrate=0.1))
                .set_checkpoint(str(tmp_path), Trigger.several_iteration(2),
                                backend="orbax"))
        opt2._load_latest_checkpoint()
        assert opt2.state["neval"] == 6
