"""Gradient accumulation: n-microbatch scan must produce the same update as
the full-batch step (mean-reduced losses), locally and on the mesh."""

import numpy as np
import jax.numpy as jnp
import pytest

from bigdl_tpu import Engine, nn
from bigdl_tpu.dataset import DataSet
from bigdl_tpu.dataset.sample import MiniBatch
from bigdl_tpu.optim import SGD, Trigger
from bigdl_tpu.optim.optimizer import LocalOptimizer
from bigdl_tpu.optim.distri_optimizer import DistriOptimizer
from bigdl_tpu.utils.random_generator import RandomGenerator


def _model(seed=11):
    RandomGenerator.set_seed(seed)
    m = nn.Sequential()
    m.add(nn.Linear(10, 24))
    m.add(nn.ReLU())
    m.add(nn.Linear(24, 5))
    m.add(nn.LogSoftMax())
    return m


def _data(batch=32, n_batches=3, seed=0):
    rng = np.random.default_rng(seed)
    return DataSet.array([
        MiniBatch(rng.normal(size=(batch, 10)).astype(np.float32),
                  rng.integers(0, 5, size=(batch,)).astype(np.int32))
        for _ in range(n_batches)])


def _train(opt_cls, accum, iters=5):
    Engine.reset()
    Engine.init(seed=0)
    opt = (opt_cls(_model(), _data(), nn.ClassNLLCriterion())
           .set_optim_method(SGD(learningrate=0.2, momentum=0.9,
                                 dampening=0.0))
           .set_gradient_accumulation(accum)
           .set_end_when(Trigger.max_iteration(iters)))
    opt.optimize()
    params = opt.model.get_params()
    return float(opt.state["loss"]), params


@pytest.mark.parametrize("accum", [2, 4])
def test_local_matches_full_batch(accum):
    loss1, p1 = _train(LocalOptimizer, 1)
    lossn, pn = _train(LocalOptimizer, accum)
    assert lossn == pytest.approx(loss1, rel=1e-4)
    import jax
    for (k1, a), (k2, b) in zip(
            jax.tree_util.tree_leaves_with_path(p1),
            jax.tree_util.tree_leaves_with_path(pn)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-4,
                                   atol=1e-6, err_msg=str(k1))


def test_sum_reduced_criterion_matches_full_batch():
    """size_average=False (summing) criteria: micro sums already total the
    full-batch sum — the update must not shrink accum-fold."""
    def train(accum):
        Engine.reset()
        Engine.init(seed=0)
        opt = (LocalOptimizer(_model(), _data(),
                              nn.ClassNLLCriterion(size_average=False))
               .set_optim_method(SGD(learningrate=0.005))
               .set_gradient_accumulation(accum)
               .set_end_when(Trigger.max_iteration(3)))
        opt.optimize()
        return float(opt.state["loss"]), opt.model.get_params()

    l1, p1 = train(1)
    l4, p4 = train(4)
    assert l4 == pytest.approx(l1, rel=1e-4)
    import jax
    for (k, a), (_, b) in zip(jax.tree_util.tree_leaves_with_path(p1),
                              jax.tree_util.tree_leaves_with_path(p4)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-4,
                                   atol=1e-6, err_msg=str(k))


def test_declared_sum_criterion_matches_full_batch():
    """Round-4 advisor follow-up: built-in sum-reducers that take no
    size_average arg (SmoothL1CriterionWithWeights — constant-divisor, so
    sum-like) must DECLARE size_average=False, or accumulation silently
    shrinks their update accum-fold."""
    assert nn.SmoothL1CriterionWithWeights.size_average is False
    assert nn.L1Cost.size_average is False

    def train(accum):
        Engine.reset()
        Engine.init(seed=0)
        rng = np.random.default_rng(3)
        data = DataSet.array([MiniBatch(
            rng.normal(size=(16, 10)).astype(np.float32),
            rng.normal(size=(16, 5)).astype(np.float32))])
        RandomGenerator.set_seed(7)
        m = nn.Sequential().add(nn.Linear(10, 5))
        opt = (LocalOptimizer(m, data, nn.SmoothL1CriterionWithWeights(num=16))
               .set_optim_method(SGD(learningrate=0.05))
               .set_gradient_accumulation(accum)
               .set_end_when(Trigger.max_iteration(3)))
        opt.optimize()
        return float(opt.state["loss"]), opt.model.get_params()

    l1, p1 = train(1)
    l4, p4 = train(4)
    assert l4 == pytest.approx(l1, rel=1e-4)
    import jax
    for (k, a), (_, b) in zip(jax.tree_util.tree_leaves_with_path(p1),
                              jax.tree_util.tree_leaves_with_path(p4)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-4,
                                   atol=1e-6, err_msg=str(k))


def test_distri_matches_full_batch():
    loss1, _ = _train(DistriOptimizer, 1)
    loss4, _ = _train(DistriOptimizer, 4)
    assert loss4 == pytest.approx(loss1, rel=1e-4)


def test_indivisible_batch_raises():
    Engine.reset()
    Engine.init(seed=0)
    opt = (LocalOptimizer(_model(), _data(batch=30), nn.ClassNLLCriterion())
           .set_optim_method(SGD(learningrate=0.1))
           .set_gradient_accumulation(4)
           .set_end_when(Trigger.max_iteration(1)))
    with pytest.raises(ValueError, match="not divisible"):
        opt.optimize()


def test_bad_n_micro_rejected():
    with pytest.raises(ValueError):
        LocalOptimizer(_model(), _data(), nn.ClassNLLCriterion()) \
            .set_gradient_accumulation(0)


class _RngProbe(nn.TensorModule):
    """Identity layer that records a scalar derived from the rng it was
    handed into its state — lets a test observe which key each microbatch
    actually received."""

    def needs_rng(self):
        return True

    def apply(self, params, state, input, *, training=False, rng=None):
        import jax
        val = (jnp.float32(-1.0) if rng is None
               else jax.random.uniform(rng, ()))
        return input, {"probe": val}


def test_dropout_rngs_differ_per_microbatch():
    """Microbatches must draw DIFFERENT randomness (fold_in per micro index),
    not replay one mask. The probe records the LAST microbatch's rng draw:
    with accumulation it must differ from the unaccumulated draw (a
    replay-rng0 regression would make them equal)."""
    def probe_value(accum):
        Engine.reset()
        Engine.init(seed=0)
        RandomGenerator.set_seed(5)
        m = nn.Sequential()
        m.add(nn.Linear(10, 16))
        m.add(_RngProbe())
        m.add(nn.Linear(16, 5))
        m.add(nn.LogSoftMax())
        opt = (LocalOptimizer(m, _data(), nn.ClassNLLCriterion())
               .set_optim_method(SGD(learningrate=0.1))
               .set_gradient_accumulation(accum)
               .set_end_when(Trigger.max_iteration(1)))
        opt.optimize()
        import jax
        leaves = jax.tree_util.tree_leaves(opt.model.get_state())
        assert len(leaves) == 1
        return float(leaves[0])

    v1 = probe_value(1)
    v2 = probe_value(2)
    assert v1 >= 0 and v2 >= 0, "probe never received an rng"
    assert v1 != v2, (
        "accumulated microbatches replayed the unaccumulated rng — "
        "fold_in per micro index is broken")
