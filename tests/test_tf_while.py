"""TF v1 raw-form while-loop import (SURVEY §2.5 TF import — the dynamic
control flow the round-4 verdict flagged): Enter/Merge/Switch/LoopCond/
NextIteration/Exit frames become a TFWhileLoop module running
``lax.while_loop``, pinned against a live TF session oracle. Scope
boundaries (TensorArray/dynamic_rnn, functional While, all-const loops)
fail loudly with pointers."""

import numpy as np
import pytest

tf = pytest.importorskip("tensorflow")
import jax.numpy as jnp

from bigdl_tpu.utils.tf.loader import TFImportError, load_frozen_graph

tf1 = tf.compat.v1


def _freeze_v1(build):
    """Build a graph with v1 raw control flow and return (graph_def, graph).

    Eager mode stays ON globally (disabling it would poison every eager-
    dependent TF-oracle test that runs later in the process — real suite
    failure); the explicit Graph context is graph-mode by itself. Only the
    control-flow-v2 toggle flips, and it is restored."""
    tf1.disable_control_flow_v2()
    try:
        g = tf1.Graph()
        with g.as_default():
            build()
    finally:
        tf1.enable_control_flow_v2()
    return g.as_graph_def(), g


def _run_tf(g, out, feeds):
    with tf1.Session(graph=g) as sess:
        return sess.run(out, feeds)


class TestWhileImport:
    def test_counter_matmul_loop_matches_tf(self):
        w_np = (np.arange(16, dtype=np.float32).reshape(4, 4) / 10.0)

        def build():
            x = tf1.placeholder(tf.float32, [2, 4], name="x")
            w = tf1.constant(w_np, name="w")
            i0 = tf.constant(0, name="i0")
            tf1.while_loop(lambda i, a: tf.less(i, 3),
                           lambda i, a: (i + 1, tf.matmul(a, w) * 0.5),
                           [i0, x], name="loop")
            # find the acc exit through the public name
        gd, g = _freeze_v1(build)
        # locate the accumulator Exit (second carried var)
        exits = sorted(n.name for n in gd.node if n.op == "Exit")
        out_name = exits[1]
        xv = np.random.RandomState(0).randn(2, 4).astype(np.float32)
        want = _run_tf(g, out_name + ":0", {"x:0": xv})
        m = load_frozen_graph(gd, [out_name], inputs=["x"])
        got = np.asarray(m.evaluate().forward(jnp.asarray(xv)))
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)

    def test_placeholder_init_counter(self):
        # a NON-const init (placeholder-driven) wires as a graph input
        def build():
            x = tf1.placeholder(tf.float32, [3], name="x")
            tf1.while_loop(lambda a: tf.less(tf.reduce_sum(a), 20.0),
                           lambda a: (a * 2.0,), [x], name="loop")
        gd, g = _freeze_v1(build)
        out_name = next(n.name for n in gd.node if n.op == "Exit")
        xv = np.array([0.5, 1.0, 0.25], np.float32)
        want = _run_tf(g, out_name + ":0", {"x:0": xv})
        m = load_frozen_graph(gd, [out_name], inputs=["x"])
        got = np.asarray(m.evaluate().forward(jnp.asarray(xv)))
        np.testing.assert_allclose(got, want, rtol=1e-5)

    def test_loop_result_feeds_downstream_ops(self):
        def build():
            x = tf1.placeholder(tf.float32, [2, 3], name="x")
            i0 = tf.constant(0, name="i0")
            _, acc = tf1.while_loop(lambda i, a: tf.less(i, 4),
                                    lambda i, a: (i + 1, a + 1.0),
                                    [i0, x], name="loop")
            tf.nn.relu(acc - 2.0, name="out")
        gd, g = _freeze_v1(build)
        xv = np.random.RandomState(1).randn(2, 3).astype(np.float32)
        want = _run_tf(g, "out:0", {"x:0": xv})
        m = load_frozen_graph(gd, ["out"], inputs=["x"])
        got = np.asarray(m.evaluate().forward(jnp.asarray(xv)))
        np.testing.assert_allclose(got, want, rtol=1e-5)

    def test_serializer_roundtrip(self, tmp_path):
        from bigdl_tpu.utils.serializer import load_module, save_module

        def build():
            x = tf1.placeholder(tf.float32, [2, 4], name="x")
            i0 = tf.constant(0, name="i0")
            tf1.while_loop(lambda i, a: tf.less(i, 3),
                           lambda i, a: (i + 1, a * 1.5 + 0.25),
                           [i0, x], name="loop")
        gd, g = _freeze_v1(build)
        exits = sorted(n.name for n in gd.node if n.op == "Exit")
        m = load_frozen_graph(gd, [exits[1]], inputs=["x"])
        xv = np.random.RandomState(2).randn(2, 4).astype(np.float32)
        want = np.asarray(m.evaluate().forward(jnp.asarray(xv)))
        save_module(m, str(tmp_path / "while.bin"))
        m2 = load_module(str(tmp_path / "while.bin"))
        got = np.asarray(m2.evaluate().forward(jnp.asarray(xv)))
        np.testing.assert_allclose(got, want, rtol=1e-6)


class TestScopeBoundaries:
    def test_tensorarray_rejected_with_pointer(self):
        # the dynamic_rnn pattern: a TensorArray accumulating per-step
        # outputs inside the loop (dynamic_rnn itself needs v1 RNN cells
        # that Keras 3 removed, so build its loop shape directly)
        def build():
            x = tf1.placeholder(tf.float32, [4, 3], name="x")
            ta0 = tf.TensorArray(tf.float32, size=4)
            i0 = tf.constant(0, name="i0")

            def body(i, ta):
                return i + 1, ta.write(i, x[i] * 2.0)

            _, ta = tf1.while_loop(lambda i, ta: tf.less(i, 4), body,
                                   [i0, ta0], name="loop")
            tf.identity(ta.stack(), name="out")
        gd, _ = _freeze_v1(build)
        with pytest.raises(TFImportError, match="recurrent stack"):
            load_frozen_graph(gd, ["out"], inputs=["x"])

    def test_functional_while_rejected_with_pointer(self):
        g = tf1.Graph()
        with g.as_default():   # control-flow v2: functional StatelessWhile
            x = tf1.placeholder(tf.float32, [3], name="x")
            tf1.while_loop(lambda a: tf.less(tf.reduce_sum(a), 10.0),
                           lambda a: (a * 2.0,), [x], name="loop")
        gd = g.as_graph_def()
        whiles = [n.name for n in gd.node
                  if n.op in ("While", "StatelessWhile")]
        if not whiles:
            pytest.skip("TF emitted raw-form loop")
        with pytest.raises(TFImportError, match="disable_control_flow_v2"):
            load_frozen_graph(gd, [whiles[0]], inputs=["x"])

    def test_all_const_inits_rejected(self):
        def build():
            x = tf1.placeholder(tf.float32, [2], name="x")
            i0 = tf.constant(0, name="i0")
            s0 = tf.constant(1.0, name="s0")
            _, s = tf1.while_loop(lambda i, s: tf.less(i, 5),
                                  lambda i, s: (i + 1, s * 2.0),
                                  [i0, s0], name="loop")
            tf.multiply(x, s, name="out")
        gd, _ = _freeze_v1(build)
        with pytest.raises(TFImportError, match="constant"):
            load_frozen_graph(gd, ["out"], inputs=["x"])
