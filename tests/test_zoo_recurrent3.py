"""Round-3 recurrent/criterion sweep: RecurrentDecoder, ConvLSTMPeephole, and
the VAE / segmentation / Caffe-style / masked criterions (SURVEY.md §2.1)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
import torch
import torch.nn.functional as F

from bigdl_tpu import nn
from bigdl_tpu.utils.random_generator import RandomGenerator
from bigdl_tpu.utils.table import T


def _np(*shape, seed=0):
    return np.random.default_rng(seed).normal(size=shape).astype(np.float32)


class TestRecurrentDecoder:
    def test_feedback_unroll_matches_manual(self):
        RandomGenerator.set_seed(0)
        cell = nn.RnnCell(4, 4)  # feedback needs input size == hidden size
        dec = nn.RecurrentDecoder(3, cell).evaluate()
        x0 = _np(2, 4)
        out = np.asarray(dec.forward(jnp.asarray(x0)))
        assert out.shape == (2, 3, 4)
        # manual unroll with the same params
        p = cell.get_params()
        h = np.zeros((2, 4), np.float32)
        x = x0
        for t in range(3):
            h = np.tanh(x @ np.asarray(p["w_ih"]).T + np.asarray(p["b_ih"])
                        + h @ np.asarray(p["w_hh"]).T + np.asarray(p["b_hh"]))
            np.testing.assert_allclose(out[:, t], h, rtol=1e-4, atol=1e-5)
            x = h

    def test_accepts_seeded_sequence_input(self):
        RandomGenerator.set_seed(0)
        dec = nn.RecurrentDecoder(2, nn.LSTM(3, 3)).evaluate()
        out = dec.forward(jnp.asarray(_np(2, 1, 3)))  # (N, 1, F) seed
        assert out.shape == (2, 2, 3)


class TestConvLSTM:
    def test_shapes_and_state(self):
        RandomGenerator.set_seed(0)
        cell = nn.ConvLSTMPeephole(2, 4, 3, 3)
        rec = nn.Recurrent(cell).evaluate()
        x = _np(2, 5, 2, 6, 6)  # (N, T, C, H, W)
        out = np.asarray(rec.forward(jnp.asarray(x)))
        assert out.shape == (2, 5, 4, 6, 6)
        assert np.isfinite(out).all()

    def test_gradients_flow(self):
        RandomGenerator.set_seed(0)
        cell = nn.ConvLSTMPeephole(2, 3, 3, 3)
        rec = nn.Recurrent(cell)

        def loss(p):
            out, _ = rec.apply(p, {}, jnp.asarray(_np(1, 3, 2, 4, 4)),
                               training=True)
            return jnp.sum(jnp.square(out))

        g = jax.grad(loss)(rec.get_params())
        leaves = jax.tree_util.tree_leaves(g)
        assert all(np.isfinite(np.asarray(l)).all() for l in leaves)
        assert any(float(jnp.abs(l).max()) > 0 for l in leaves)

    def test_stride_rejected(self):
        with pytest.raises(ValueError, match="stride 1"):
            nn.ConvLSTMPeephole(2, 3, 3, 3, stride=2)


class TestVAECriterions:
    def test_kld_closed_form(self):
        mu = _np(4, 6)
        lv = _np(4, 6, seed=1)
        out = float(nn.KLDCriterion().forward(
            T(jnp.asarray(mu), jnp.asarray(lv)), jnp.zeros(())))
        ref = 0.5 * np.sum(mu ** 2 + np.exp(lv) - 1.0 - lv, axis=-1).mean()
        np.testing.assert_allclose(out, ref, rtol=1e-5)

    def test_gaussian_nll_oracle(self):
        mu, lv, x = _np(4, 6), _np(4, 6, seed=1), _np(4, 6, seed=2)
        out = float(nn.GaussianCriterion().forward(
            T(jnp.asarray(mu), jnp.asarray(lv)), jnp.asarray(x)))
        ref = F.gaussian_nll_loss(
            torch.tensor(mu), torch.tensor(x), torch.tensor(np.exp(lv)),
            full=True, reduction="sum", eps=0.0).item()
        np.testing.assert_allclose(out, ref, rtol=1e-4)

    def test_vae_trains(self):
        """GaussianSampler + KLD + reconstruction — the full VAE slice."""
        from bigdl_tpu.dataset.dataset import DataSet
        from bigdl_tpu.dataset.sample import MiniBatch
        from bigdl_tpu.optim import SGD
        from bigdl_tpu.optim.optimizer import LocalOptimizer
        from bigdl_tpu.optim.trigger import Trigger

        RandomGenerator.set_seed(0)
        inp = nn.Input()
        enc = nn.Linear(8, 16).inputs(inp)
        encr = nn.ReLU().inputs(enc)
        mu = nn.Linear(16, 4).inputs(encr)
        lv = nn.Linear(16, 4).inputs(encr)
        z = nn.GaussianSampler().inputs(mu, lv)
        dec = nn.Linear(4, 8).inputs(z)
        model = nn.Graph(inp, dec)
        x = _np(64, 8)
        ds = DataSet.array([MiniBatch(x[i:i + 16], x[i:i + 16])
                            for i in range(0, 64, 16)])
        opt = LocalOptimizer(model, ds, nn.MSECriterion())
        opt.set_optim_method(SGD(learningrate=0.05))
        opt.set_end_when(Trigger.max_iteration(8))
        opt.optimize()
        assert np.isfinite(opt.state["loss"])


class TestCaffeStyleCriterions:
    def test_softmax_with_criterion_matches_cross_entropy(self):
        logits = _np(6, 5)
        y = np.random.default_rng(0).integers(0, 5, size=(6,)).astype(np.int32)
        out = float(nn.SoftmaxWithCriterion().forward(
            jnp.asarray(logits), jnp.asarray(y)))
        ref = F.cross_entropy(torch.tensor(logits),
                              torch.tensor(y.astype(np.int64))).item()
        np.testing.assert_allclose(out, ref, rtol=1e-5)

    def test_softmax_with_ignore_label(self):
        logits = _np(6, 5)
        y = np.array([0, 1, 2, 3, 4, 2], np.int32)
        out = float(nn.SoftmaxWithCriterion(ignore_label=2).forward(
            jnp.asarray(logits), jnp.asarray(y)))
        keep = y != 2
        ref = F.cross_entropy(torch.tensor(logits[keep]),
                              torch.tensor(y[keep].astype(np.int64))).item()
        np.testing.assert_allclose(out, ref, rtol=1e-5)

    def test_softmax_with_spatial_input(self):
        logits = _np(2, 4, 3, 3)
        y = np.random.default_rng(1).integers(0, 4, size=(2, 3, 3)).astype(np.int32)
        out = float(nn.SoftmaxWithCriterion().forward(
            jnp.asarray(logits), jnp.asarray(y)))
        ref = F.cross_entropy(torch.tensor(logits),
                              torch.tensor(y.astype(np.int64))).item()
        np.testing.assert_allclose(out, ref, rtol=1e-5)

    def test_dice(self):
        x = np.abs(_np(3, 10))
        y = (np.abs(_np(3, 10, seed=1)) > 0.5).astype(np.float32)
        out = float(nn.DiceCoefficientCriterion(epsilon=1.0).forward(
            jnp.asarray(x), jnp.asarray(y)))
        inter = (x * y).sum(1)
        ref = (1 - 2 * inter / (x.sum(1) + y.sum(1) + 1.0)).mean()
        np.testing.assert_allclose(out, ref, rtol=1e-5)

    def test_categorical_cross_entropy(self):
        p = np.abs(_np(4, 3)) + 0.1
        p = p / p.sum(1, keepdims=True)
        y = np.eye(3, dtype=np.float32)[[0, 2, 1, 0]]
        out = float(nn.CategoricalCrossEntropy().forward(
            jnp.asarray(p), jnp.asarray(y)))
        ref = -np.mean(np.log(p[np.arange(4), [0, 2, 1, 0]]))
        np.testing.assert_allclose(out, ref, rtol=1e-5)


class TestMaskedAndWeighted:
    def test_time_distributed_mask(self):
        logits = _np(2, 4, 5)
        y = np.array([[1, 2, 0, 0], [3, 4, 1, 0]], np.int32)  # 0 = padding
        out = float(nn.TimeDistributedMaskCriterion(
            nn.CrossEntropyCriterion(), padding_value=0).forward(
            jnp.asarray(logits), jnp.asarray(y)))
        keep = y.reshape(-1) != 0
        ref = F.cross_entropy(
            torch.tensor(logits.reshape(-1, 5)[keep]),
            torch.tensor(y.reshape(-1)[keep].astype(np.int64))).item()
        np.testing.assert_allclose(out, ref, rtol=1e-5)

    def test_smooth_l1_with_weights(self):
        x, t = _np(3, 4), _np(3, 4, seed=1)
        iw = np.abs(_np(3, 4, seed=2))
        ow = np.abs(_np(3, 4, seed=3))
        sigma, num = 2.0, 3
        out = float(nn.SmoothL1CriterionWithWeights(sigma, num).forward(
            jnp.asarray(x), T(jnp.asarray(t), jnp.asarray(iw), jnp.asarray(ow))))
        d = iw * (x - t)
        s2 = sigma * sigma
        l = np.where(np.abs(d) < 1 / s2, 0.5 * s2 * d * d,
                     np.abs(d) - 0.5 / s2)
        np.testing.assert_allclose(out, (ow * l).sum() / num, rtol=1e-5)

    def test_transformer_criterion(self):
        RandomGenerator.set_seed(0)
        feat = nn.Sequential().add(nn.Linear(6, 4)).add(nn.ReLU())
        crit = nn.TransformerCriterion(nn.MSECriterion(), feat, feat)
        x, t = _np(3, 6), _np(3, 6, seed=1)
        out = float(crit.forward(jnp.asarray(x), jnp.asarray(t)))
        fx = np.asarray(feat.evaluate().forward(jnp.asarray(x)))
        ft = np.asarray(feat.evaluate().forward(jnp.asarray(t)))
        np.testing.assert_allclose(out, np.mean((fx - ft) ** 2), rtol=1e-5)
