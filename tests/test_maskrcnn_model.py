"""Mask-R-CNN zoo model (models/maskrcnn): the round-5 detection family
composed end-to-end — backbone pyramid → FPN → RPN → box head → per-class
decode/NMS → mask head — as ONE static-shape program. Shape/contract,
jit-compile, and serializer round-trip coverage."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from bigdl_tpu import nn
from bigdl_tpu.models.maskrcnn import MaskRCNN, MaskRCNNBackbone
from bigdl_tpu.utils.random_generator import RandomGenerator


def _img(h=128, w=128, seed=0):
    return jnp.asarray(np.random.default_rng(seed)
                       .normal(size=(1, 3, h, w)).astype(np.float32))


class TestMaskRCNN:
    def test_backbone_pyramid_shapes(self):
        RandomGenerator.set_seed(0)
        b = MaskRCNNBackbone(out_channels=32)
        out, _ = b.apply(b.get_params(), b.get_state(), _img())
        lvls = list(out.values())
        assert [o.shape for o in lvls] == [
            (1, 32, 32, 32), (1, 32, 16, 16), (1, 32, 8, 8)]

    def test_end_to_end_contract(self):
        RandomGenerator.set_seed(1)
        m = MaskRCNN(n_classes=4, image_size=(128, 128), out_channels=32,
                     post_nms_topn=30, max_per_image=8).evaluate()
        dets, valid, masks = m.forward(_img(seed=2)).values()
        assert dets.shape == (8, 6)
        assert valid.shape == (8,)
        assert masks.shape == (8, 4, 28, 28)
        live = np.asarray(dets)[np.asarray(valid)]
        if len(live):
            assert ((live[:, 0] >= 1) & (live[:, 0] < 4)).all()
            assert (live[:, 2:] >= 0).all() and (live[:, 2:] <= 127).all()

    def test_jits_to_one_program(self):
        RandomGenerator.set_seed(2)
        m = MaskRCNN(n_classes=3, image_size=(64, 64), out_channels=16,
                     post_nms_topn=12, max_per_image=4).evaluate()
        params, mstate = m.get_params(), m.get_state()

        @jax.jit
        def serve(p, x):
            out, _ = m.apply(p, mstate, x, training=False)
            return tuple(out.values())

        dets, valid, masks = serve(params, _img(64, 64, seed=3))
        assert dets.shape == (4, 6) and masks.shape == (4, 3, 28, 28)

    def test_training_refused_loudly(self):
        m = MaskRCNN(n_classes=3, image_size=(64, 64), out_channels=16)
        with pytest.raises(ValueError, match="inference"):
            m.apply(m.get_params(), m.get_state(), _img(64, 64),
                    training=True)

    def test_serializer_roundtrip(self, tmp_path):
        from bigdl_tpu.utils.serializer import load_module, save_module

        RandomGenerator.set_seed(3)
        m = MaskRCNN(n_classes=3, image_size=(64, 64), out_channels=16,
                     post_nms_topn=12, max_per_image=4).evaluate()
        x = _img(64, 64, seed=4)
        want = m.forward(x)
        save_module(m, str(tmp_path / "mrcnn.bin"))
        m2 = load_module(str(tmp_path / "mrcnn.bin")).evaluate()
        got = m2.forward(x)
        for a, b in zip(want.values(), got.values()):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-5, atol=1e-5)


def test_mismatched_image_size_rejected():
    RandomGenerator.set_seed(4)
    m = MaskRCNN(n_classes=3, image_size=(64, 64), out_channels=16).evaluate()
    with pytest.raises(ValueError, match="64x64"):
        m.forward(_img(128, 128, seed=5))
