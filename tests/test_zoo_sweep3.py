"""Round-3 second layer sweep: elementwise, grad-trick, table and shape layers
(SURVEY.md §2.1 layer zoo). Torch oracles where torch has the op."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
import torch
import torch.nn.functional as F

from bigdl_tpu import nn
from bigdl_tpu.utils.random_generator import RandomGenerator
from bigdl_tpu.utils.table import T


def _np(*shape, seed=0):
    return np.random.default_rng(seed).normal(size=shape).astype(np.float32)


class TestActivationsExt:
    def test_binary_threshold(self):
        x = _np(3, 4)
        out = np.asarray(nn.BinaryThreshold(0.1).evaluate().forward(jnp.asarray(x)))
        np.testing.assert_allclose(out, (x > 0.1).astype(np.float32))

    def test_logsigmoid_oracle(self):
        x = _np(3, 4)
        out = np.asarray(nn.LogSigmoid().evaluate().forward(jnp.asarray(x)))
        np.testing.assert_allclose(out, F.logsigmoid(torch.tensor(x)).numpy(),
                                   rtol=1e-5, atol=1e-6)

    def test_tanhshrink_oracle(self):
        x = _np(3, 4)
        out = np.asarray(nn.TanhShrink().evaluate().forward(jnp.asarray(x)))
        np.testing.assert_allclose(out, F.tanhshrink(torch.tensor(x)).numpy(),
                                   rtol=1e-5, atol=1e-6)


class TestGradTricks:
    def test_gradient_reversal(self):
        m = nn.GradientReversal(the_lambda=2.0)
        x = jnp.asarray(_np(3, 4))
        out = m.forward(x)
        np.testing.assert_allclose(np.asarray(out), np.asarray(x))
        gi = m.backward(x, jnp.ones_like(x))
        np.testing.assert_allclose(np.asarray(gi), -2.0 * np.ones((3, 4)),
                                   rtol=1e-6)

    def test_gradient_reversal_inside_jit_grad(self):
        m = nn.GradientReversal(the_lambda=0.5)

        @jax.jit
        def loss(x):
            out, _ = m.apply({}, {}, x, training=True)
            return jnp.sum(out)

        g = jax.grad(loss)(jnp.ones((2, 2)))
        np.testing.assert_allclose(np.asarray(g), -0.5 * np.ones((2, 2)))

    def test_l1_penalty(self):
        m = nn.L1Penalty(l1weight=0.3).training()
        x = jnp.asarray(_np(3, 4))
        np.testing.assert_allclose(np.asarray(m.forward(x)), np.asarray(x))
        gi = m.backward(x, jnp.zeros_like(x))
        np.testing.assert_allclose(np.asarray(gi),
                                   0.3 * np.sign(np.asarray(x)), rtol=1e-6)
        # eval mode: pure identity, no sparsity gradient
        gi_eval = nn.L1Penalty(0.3).evaluate().backward(x, jnp.zeros_like(x))
        np.testing.assert_allclose(np.asarray(gi_eval), np.zeros((3, 4)))


class TestScaleHighwaySampler:
    def test_scale(self):
        m = nn.Scale((3, 1, 1))
        w = _np(3, 1, 1, seed=1)
        b = _np(3, 1, 1, seed=2)
        m.set_params({"weight": jnp.asarray(w), "bias": jnp.asarray(b)})
        x = _np(2, 3, 4, 4)
        out = np.asarray(m.evaluate().forward(jnp.asarray(x)))
        np.testing.assert_allclose(out, x * w[None] + b[None],
                                   rtol=1e-5, atol=1e-7)

    def test_highway_carry_behavior(self):
        RandomGenerator.set_seed(0)
        m = nn.Highway(8)
        # force the gate fully closed -> output == input (carry path)
        p = m.get_params()
        p["gate_weight"] = jnp.zeros_like(p["gate_weight"])
        p["gate_bias"] = jnp.full_like(p["gate_bias"], -1e9)
        m.set_params(p)
        x = jnp.asarray(_np(4, 8))
        np.testing.assert_allclose(np.asarray(m.evaluate().forward(x)),
                                   np.asarray(x), rtol=1e-6)

    def test_gaussian_sampler_stats(self):
        RandomGenerator.set_seed(0)
        m = nn.GaussianSampler().training()
        mu = np.full((20000,), 1.5, np.float32)
        log_var = np.full((20000,), np.log(0.25), np.float32)
        out = np.asarray(m.forward(T(jnp.asarray(mu), jnp.asarray(log_var))))
        assert abs(out.mean() - 1.5) < 0.02
        assert abs(out.std() - 0.5) < 0.02
        # eval mode returns the mean
        out_eval = np.asarray(m.evaluate().forward(
            T(jnp.asarray(mu), jnp.asarray(log_var))))
        np.testing.assert_allclose(out_eval, mu)

    def test_pairwise_distance_oracle(self):
        a, b = _np(5, 7), _np(5, 7, seed=1)
        out = np.asarray(nn.PairwiseDistance(2).evaluate()
                         .forward(T(jnp.asarray(a), jnp.asarray(b))))
        ref = F.pairwise_distance(torch.tensor(a), torch.tensor(b)).numpy()
        np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-5)


class TestTableOps:
    def test_narrow_table(self):
        xs = [jnp.asarray(_np(2, 2, seed=i)) for i in range(4)]
        out = nn.NarrowTable(2, 2).evaluate().forward(T(*xs))
        got = out.values()
        np.testing.assert_allclose(np.asarray(got[0]), np.asarray(xs[1]))
        np.testing.assert_allclose(np.asarray(got[1]), np.asarray(xs[2]))
        single = nn.NarrowTable(3).evaluate().forward(T(*xs))
        np.testing.assert_allclose(np.asarray(single), np.asarray(xs[2]))

    def test_pack(self):
        xs = [jnp.asarray(_np(2, 3, seed=i)) for i in range(3)]
        out = np.asarray(nn.Pack(1).evaluate().forward(T(*xs)))
        np.testing.assert_allclose(out, np.stack([np.asarray(x) for x in xs], 0))

    def test_cave_table(self):
        a, b, c = (_np(2, 3, seed=i) for i in range(3))
        out = np.asarray(nn.CAveTable().evaluate().forward(
            T(jnp.asarray(a), jnp.asarray(b), jnp.asarray(c))))
        np.testing.assert_allclose(out, (a + b + c) / 3, rtol=1e-6)

    def test_bifurcate_split(self):
        x = _np(4, 6)
        out = nn.BifurcateSplitTable(2).evaluate().forward(jnp.asarray(x))
        a, b = out.values()
        np.testing.assert_allclose(np.asarray(a), x[:, :3])
        np.testing.assert_allclose(np.asarray(b), x[:, 3:])

    def test_mixture_table(self):
        g = np.abs(_np(4, 3))
        g = g / g.sum(1, keepdims=True)
        experts = [_np(4, 5, seed=i) for i in range(3)]
        out = np.asarray(nn.MixtureTable().evaluate().forward(
            T(jnp.asarray(g), T(*[jnp.asarray(e) for e in experts]))))
        ref = sum(g[:, i:i + 1] * experts[i] for i in range(3))
        np.testing.assert_allclose(out, ref, rtol=1e-5)

    def test_masked_select_eager(self):
        x = _np(3, 4)
        mask = (x > 0).astype(np.float32)
        out = np.asarray(nn.MaskedSelect().evaluate().forward(
            T(jnp.asarray(x), jnp.asarray(mask))))
        np.testing.assert_allclose(out, x[x > 0])


class TestShapeOpsExt:
    def test_tile(self):
        x = _np(2, 3)
        out = np.asarray(nn.Tile(2, 3).evaluate().forward(jnp.asarray(x)))
        np.testing.assert_allclose(out, np.tile(x, (1, 3)))

    def test_reverse(self):
        x = _np(2, 5)
        out = np.asarray(nn.Reverse(2).evaluate().forward(jnp.asarray(x)))
        np.testing.assert_allclose(out, x[:, ::-1])

    def test_index(self):
        x = _np(5, 3)
        idx = np.array([3, 0, 1], np.int32)
        out = np.asarray(nn.Index(1).evaluate().forward(
            T(jnp.asarray(x), jnp.asarray(idx))))
        np.testing.assert_allclose(out, x[idx])

    def test_infer_reshape(self):
        x = _np(2, 3, 4)
        out = np.asarray(nn.InferReshape([0, -1], batch_mode=True)
                         .evaluate().forward(jnp.asarray(x)))
        assert out.shape == (2, 3, 4) or out.shape == (2, 3, 4)
        out2 = np.asarray(nn.InferReshape([-1]).evaluate().forward(jnp.asarray(x)))
        assert out2.shape == (24,)
        out3 = np.asarray(nn.InferReshape([6, -1]).evaluate()
                          .forward(jnp.asarray(x)))
        assert out3.shape == (6, 4)


class TestTrainThrough:
    def test_highway_trains_in_sequential(self):
        """New layers must compose with the one-jit training step."""
        from bigdl_tpu.dataset.dataset import DataSet
        from bigdl_tpu.dataset.sample import MiniBatch
        from bigdl_tpu.optim import SGD
        from bigdl_tpu.optim.optimizer import LocalOptimizer
        from bigdl_tpu.optim.trigger import Trigger

        RandomGenerator.set_seed(0)
        model = nn.Sequential()
        model.add(nn.Linear(6, 8)).add(nn.Highway(8)).add(nn.L1Penalty(1e-4))
        model.add(nn.Linear(8, 3)).add(nn.LogSoftMax())
        x = _np(32, 6)
        y = np.random.default_rng(0).integers(0, 3, size=(32,)).astype(np.int32)
        ds = DataSet.array([MiniBatch(x[i:i + 8], y[i:i + 8])
                            for i in range(0, 32, 8)])
        opt = LocalOptimizer(model, ds, nn.ClassNLLCriterion())
        opt.set_optim_method(SGD(learningrate=0.1))
        opt.set_end_when(Trigger.max_iteration(6))
        opt.optimize()
        assert np.isfinite(opt.state["loss"])


class TestReviewFixes3:
    def test_gradient_reversal_set_lambda_after_trace(self):
        m = nn.GradientReversal(1.0)
        x = jnp.asarray(_np(2, 3))
        m.backward(x, jnp.ones_like(x))  # bakes lambda=1 into the trace
        m.set_lambda(3.0)
        gi = m.backward(x, jnp.ones_like(x))
        np.testing.assert_allclose(np.asarray(gi), -3.0 * np.ones((2, 3)))

    def test_mixture_table_tensor_experts(self):
        g = np.abs(_np(4, 3))
        g = g / g.sum(1, keepdims=True)
        experts = _np(4, 3, 5, seed=1)  # pre-stacked, expert axis = dim 2
        out = np.asarray(nn.MixtureTable(2).evaluate().forward(
            T(jnp.asarray(g), jnp.asarray(experts))))
        ref = np.einsum("ne,nef->nf", g, experts)
        np.testing.assert_allclose(out, ref, rtol=1e-5)

    def test_highway_rejects_parametric_activation(self):
        with pytest.raises(ValueError, match="parameter-free"):
            nn.Highway(8, activation=nn.PReLU())
