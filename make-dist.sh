#!/usr/bin/env bash
# Build a distributable artifact set — the reference's make-dist.sh analog
# (SURVEY.md §2.5 Build system): dist/ gets the wheel plus the launcher,
# conf reference, and docs, zipped as bigdl-tpu-dist.zip.
set -euo pipefail
cd "$(dirname "${BASH_SOURCE[0]}")"

rm -rf dist build
mkdir -p dist
pip wheel . --no-deps --no-build-isolation -w dist >/dev/null
cp -r conf scripts docs dist/
( cd dist && zip -qr bigdl-tpu-dist.zip . ) 2>/dev/null \
  || tar -czf dist/bigdl-tpu-dist.tar.gz -C dist \
       $(cd dist && ls | grep -v 'bigdl-tpu-dist')
echo "dist/ contents:"
ls dist
